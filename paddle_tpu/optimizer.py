"""Optimizers: build backward + update ops into the program.

Reference: python/paddle/fluid/optimizer.py (Optimizer base :488 backward,
:557 apply_gradients, :641 minimize; 18 subclasses). The update ops land in
the same Program and therefore compile into the SAME XLA computation as
fwd+bwd — one fused step, no per-param kernel launches.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .backward import append_backward
from .clip import get_gradient_clip
from .framework import Variable, default_main_program, unique_name
from .layers.tensor import create_global_var

__all__ = [
    "Optimizer", "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
    "LarsMomentum", "LarsMomentumOptimizer", "Adagrad", "AdagradOptimizer",
    "DecayedAdagrad", "DecayedAdagradOptimizer", "Adam", "AdamOptimizer",
    "AdamW", "AdamWOptimizer", "Adamax", "AdamaxOptimizer", "Adadelta",
    "AdadeltaOptimizer", "RMSProp", "RMSPropOptimizer", "Ftrl",
    "FtrlOptimizer", "Lamb", "LambOptimizer", "Dpsgd", "DpsgdOptimizer",
    "ExponentialMovingAverage", "ModelAverage", "LookaheadOptimizer",
    "RecomputeOptimizer", "PipelineOptimizer", "DGCMomentumOptimizer",
    "GradientMergeOptimizer",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._lr_var = None
        self.type = getattr(self, "type", "sgd")

    # -- learning rate ---------------------------------------------------
    def _create_lr_var(self):
        from .dygraph.learning_rate_scheduler import LearningRateDecay
        if isinstance(self._learning_rate, LearningRateDecay):
            raise TypeError(
                "dygraph LearningRateDecay objects only work inside "
                "dygraph.guard(); static-graph programs use "
                "layers.learning_rate_scheduler.* (exponential_decay, "
                "piecewise_decay, ...)")
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
        elif self._lr_var is None:
            self._lr_var = create_global_var(
                [1], float(self._learning_rate), "float32", persistable=True,
                name=unique_name.generate("learning_rate"))
        return self._lr_var

    @property
    def learning_rate_var(self):
        return self._create_lr_var()

    def current_step_lr(self):
        return self._create_lr_var()

    # -- accumulators ----------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype=None):
        acc = self._accumulators.setdefault(name, {})
        if param.name in acc:
            return acc[param.name]
        v = create_global_var(
            shape or list(param.shape), fill_value, dtype or param.dtype,
            persistable=True,
            name=unique_name.generate(f"{param.name}_{name}"))
        acc[param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, params_grads):
        pass

    # -- op emission (subclass hook) -------------------------------------
    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # -- public API ------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        block = default_main_program().current_block()
        # regularization (reference: regularizer.py append_regularization_ops)
        out = []
        for p, g in params_grads:
            reg = p.regularizer or (self.regularization if
                                    hasattr(p, "regularizer") else None)
            reg = reg or self.regularization
            if reg is not None:
                g = reg.append_regularization_op(p, g)
            out.append((p, g))
        params_grads = out
        clip = get_gradient_clip()
        if clip is not None:
            params_grads = clip.apply(params_grads)
        self._create_lr_var()
        self._create_accumulators(block, [p for p, _ in params_grads])
        opt_ops = []
        for p, g in params_grads:
            opt_ops.append(self._append_optimize_op(block, (p, g)))
        self._finish_update(block, params_grads)
        return opt_ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from . import dygraph
        if dygraph.enabled():
            return self._minimize_dygraph(parameter_list, no_grad_set)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        opt_ops = self.apply_gradients(params_grads)
        return opt_ops, params_grads

    # -- eager (dygraph) path --------------------------------------------
    def _minimize_dygraph(self, parameter_list, no_grad_set=None):
        """Apply one eager update after loss.backward() populated
        param.grad (reference dygraph: optimizer.minimize(loss,
        parameter_list=model.parameters())). Mirrors the static
        apply_gradients pipeline: regularization, then gradient clip,
        then the update rule."""
        if parameter_list is None:
            raise ValueError(
                "minimize in dygraph mode needs parameter_list "
                "(e.g. model.parameters())")
        skip = {getattr(v, "name", v) for v in (no_grad_set or ())}
        lr = self._dygraph_step_lr()
        state = getattr(self, "_dy_state", None)
        if state is None:
            state = self._dy_state = {}
        pgs = []
        for p in parameter_list:
            if not getattr(p, "trainable", True) or p.grad is None \
                    or p.name in skip:
                continue
            w = np.asarray(p.value, np.float32)
            g = np.asarray(p.grad, np.float32)
            if self.regularization is not None:
                g = g + self._eager_regularization(w)
            pgs.append((p, w, g))
        pgs = self._eager_clip(pgs)
        for p, w, g in pgs:
            dtype = np.asarray(p.value).dtype
            new = self._dygraph_update(w, g, lr,
                                       state.setdefault(p.name, {}))
            p.set_value(np.asarray(new, dtype))
        return [], [(p, g) for p, _, g in pgs]

    def _eager_regularization(self, w):
        from .regularizer import L1DecayRegularizer, L2DecayRegularizer
        reg = self.regularization
        if isinstance(reg, L2DecayRegularizer):
            return reg.coeff * w
        if isinstance(reg, L1DecayRegularizer):
            return reg.coeff * np.sign(w)
        raise NotImplementedError(
            f"dygraph regularization for {type(reg).__name__}")

    def _eager_clip(self, pgs):
        from .clip import (GradientClipByGlobalNorm, GradientClipByNorm,
                           GradientClipByValue, get_gradient_clip)
        clip = get_gradient_clip()
        if clip is None or not pgs:
            return pgs
        if isinstance(clip, GradientClipByValue):
            lo = clip.min if clip.min is not None else -clip.max
            return [(p, w, np.clip(g, lo, clip.max)) for p, w, g in pgs]
        if isinstance(clip, GradientClipByNorm):
            out = []
            for p, w, g in pgs:
                n = float(np.linalg.norm(g))
                s = clip.clip_norm / max(n, clip.clip_norm)
                out.append((p, w, g * s))
            return out
        if isinstance(clip, GradientClipByGlobalNorm):
            gn = float(np.sqrt(sum(float((g * g).sum())
                                   for _, _, g in pgs)))
            s = clip.clip_norm / max(gn, clip.clip_norm)
            return [(p, w, g * s) for p, w, g in pgs]
        raise NotImplementedError(
            f"dygraph gradient clip for {type(clip).__name__}")

    def _dygraph_step_lr(self) -> float:
        from .dygraph.learning_rate_scheduler import LearningRateDecay
        if isinstance(self._learning_rate, LearningRateDecay):
            return self._learning_rate.step()
        return float(self._learning_rate)

    def _dygraph_update(self, w, g, lr, state):
        raise NotImplementedError(
            f"{type(self).__name__} has no eager (dygraph) update rule; "
            f"train it through the static-graph path or use "
            f"SGD/Momentum/Adagrad/Adam/AdamW in dygraph mode")


def _lr_input(self, param):
    lr = self._lr_var
    scale = 1.0
    if getattr(param, "optimize_attr", None):
        scale = param.optimize_attr.get("learning_rate", 1.0)
    if scale != 1.0:
        from .layers.nn import scale as scale_layer
        return scale_layer(lr, scale=scale)
    return lr


class SGDOptimizer(Optimizer):
    type = "sgd"

    def _dygraph_update(self, w, g, lr, state):
        return w - lr * g

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "sgd",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "LearningRate": [_lr_input(self, p).name]},
            outputs={"ParamOut": [p.name]}, infer_shape=False)


class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _dygraph_update(self, w, g, lr, state):
        v = state.get("velocity")
        v = g if v is None else self._momentum * v + g
        state["velocity"] = v
        if self._use_nesterov:
            return w - lr * (g + self._momentum * v)
        return w - lr * v

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "Velocity": [v.name],
                    "LearningRate": [_lr_input(self, p).name]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov}, infer_shape=False)


class LarsMomentumOptimizer(MomentumOptimizer):
    type = "lars_momentum"

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, momentum, **kw)
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _dygraph_update(self, w, g, lr, state):
        # LARS: layerwise-adapted local lr (lars_momentum_op)
        wn = float(np.linalg.norm(w))
        gn = float(np.linalg.norm(g))
        wd = self._lars_weight_decay
        local_lr = lr * self._lars_coeff * wn / max(gn + wd * wn, 1e-12) \
            if wn > 0 else lr
        v = state.get("velocity")
        step = local_lr * (g + wd * w)
        v = step if v is None else self._momentum * v + step
        state["velocity"] = v
        return w - v

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "Velocity": [v.name],
                    "LearningRate": [_lr_input(self, p).name]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay},
            infer_shape=False)


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value
                 =0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._init_acc)

    def _dygraph_update(self, w, g, lr, state):
        acc = state.get("moment")
        acc = (np.full_like(g, self._init_acc) if acc is None else acc) \
            + g * g
        state["moment"] = acc
        return w - lr * g / (np.sqrt(acc) + self._epsilon)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "Moment": [m.name],
                    "LearningRate": [_lr_input(self, p).name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"epsilon": self._epsilon}, infer_shape=False)


class DecayedAdagradOptimizer(AdagradOptimizer):
    type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, epsilon=epsilon, **kw)
        self._decay = decay

    def _dygraph_update(self, w, g, lr, state):
        acc = state.get("moment")
        acc = np.zeros_like(g) if acc is None else acc
        acc = self._decay * acc + (1 - self._decay) * g * g
        state["moment"] = acc
        return w - lr * g / (np.sqrt(acc) + self._epsilon)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "Moment": [m.name],
                    "LearningRate": [_lr_input(self, p).name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
            infer_shape=False)


class _AdamBase(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow", p, fill_value=self._beta2,
                                  shape=[1])

    def _dygraph_adam_step(self, w, g, lr, state):
        m1 = state.get("m1", np.zeros_like(w))
        m2 = state.get("m2", np.zeros_like(w))
        t = state.get("t", 0) + 1
        m1 = self._beta1 * m1 + (1 - self._beta1) * g
        m2 = self._beta2 * m2 + (1 - self._beta2) * g * g
        state.update(m1=m1, m2=m2, t=t)
        mh = m1 / (1 - self._beta1 ** t)
        vh = m2 / (1 - self._beta2 ** t)
        return mh / (np.sqrt(vh) + self._epsilon)

    def _adam_io(self, p, g):
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow", p)
        b2p = self._get_accumulator("beta2_pow", p)
        ins = {"Param": [p.name], "Grad": [g.name], "Moment1": [m1.name],
               "Moment2": [m2.name], "Beta1Pow": [b1p.name],
               "Beta2Pow": [b2p.name],
               "LearningRate": [_lr_input(self, p).name]}
        outs = {"ParamOut": [p.name], "Moment1Out": [m1.name],
                "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
                "Beta2PowOut": [b2p.name]}
        return ins, outs


class AdamOptimizer(_AdamBase):
    type = "adam"

    def _dygraph_update(self, w, g, lr, state):
        return w - lr * self._dygraph_adam_step(w, g, lr, state)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        ins, outs = self._adam_io(p, g)
        return block.append_op(
            "adam", inputs=ins, outputs=outs,
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon}, infer_shape=False)


class AdamWOptimizer(_AdamBase):
    type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._coeff = weight_decay

    def _dygraph_update(self, w, g, lr, state):
        # decoupled weight decay (AdamW): decay applied on the param
        return w - lr * (self._dygraph_adam_step(w, g, lr, state)
                         + self._coeff * w)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        ins, outs = self._adam_io(p, g)
        return block.append_op(
            "adamw", inputs=ins, outputs=outs,
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "coeff": self._coeff},
            infer_shape=False)


class LambOptimizer(_AdamBase):
    type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kw)
        self._weight_decay = lamb_weight_decay

    def _append_optimize_op(self, block, pg):
        p, g = pg
        ins, outs = self._adam_io(p, g)
        return block.append_op(
            "lamb", inputs=ins, outputs=outs,
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "weight_decay": self._weight_decay}, infer_shape=False)


class AdamaxOptimizer(Optimizer):
    type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        inf = self._get_accumulator("inf_norm", p)
        b1p = self._get_accumulator("beta1_pow", p)
        op = block.append_op(
            "adamax",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "Moment": [m.name], "InfNorm": [inf.name],
                    "Beta1Pow": [b1p.name],
                    "LearningRate": [_lr_input(self, p).name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name],
                     "InfNormOut": [inf.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon}, infer_shape=False)
        # beta1_pow updated outside the op (reference _finish_update)
        block.append_op("scale", inputs={"X": [b1p.name]},
                        outputs={"Out": [b1p.name]},
                        attrs={"scale": self._beta1}, infer_shape=False)
        return op


class AdadeltaOptimizer(Optimizer):
    type = "adadelta"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        sg = self._get_accumulator("avg_squared_grad", p)
        su = self._get_accumulator("avg_squared_update", p)
        return block.append_op(
            "adadelta",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "AvgSquaredGrad": [sg.name],
                    "AvgSquaredUpdate": [su.name]},
            outputs={"ParamOut": [p.name], "AvgSquaredGradOut": [sg.name],
                     "AvgSquaredUpdateOut": [su.name]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
            infer_shape=False)


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("momentum", p)
        ins = {"Param": [p.name], "Grad": [g.name],
               "MeanSquare": [ms.name], "Moment": [mom.name],
               "LearningRate": [_lr_input(self, p).name]}
        outs = {"ParamOut": [p.name], "MeanSquareOut": [ms.name],
                "MomentOut": [mom.name]}
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            ins["MeanGrad"] = [mg.name]
            outs["MeanGradOut"] = [mg.name]
        return block.append_op(
            "rmsprop", inputs=ins, outputs=outs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered},
            infer_shape=False)


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            "ftrl",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "SquaredAccumulator": [sq.name],
                    "LinearAccumulator": [lin.name],
                    "LearningRate": [_lr_input(self, p).name]},
            outputs={"ParamOut": [p.name], "SquaredAccumOut": [sq.name],
                     "LinearAccumOut": [lin.name]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power}, infer_shape=False)


class DpsgdOptimizer(Optimizer):
    type = "dpsgd"

    def __init__(self, learning_rate, clip=10.0, batch_size=16.0,
                 sigma=1.0, **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "dpsgd",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "LearningRate": [_lr_input(self, p).name]},
            outputs={"ParamOut": [p.name]},
            attrs={"clip": self._clip, "batch_size": self._batch_size,
                   "sigma": self._sigma}, infer_shape=False)


class DGCMomentumOptimizer(MomentumOptimizer):
    """Deep Gradient Compression momentum (reference optimizer.py:870).

    On TPU the allreduce rides ICI inside the compiled program, where
    XLA's latency-hiding scheduler overlaps it with compute — top-k
    sparsification would *break* the static-shape collective. We keep the
    API and run dense momentum; ranked top-k compression over DCN is a
    multi-slice concern for a later round.
    """

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 **kw):
        kw.pop("rampup_step", None)
        kw.pop("sparsity", None)
        super().__init__(learning_rate, momentum, **kw)


class ExponentialMovingAverage:
    """EMA of params (reference optimizer.py:2786): shadow vars updated in
    the step program; apply()/restore() swap them in for eval."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadows = {}
        self._backups = {}

    def update(self):
        block = default_main_program().current_block()
        params = [p for p in block.program.all_parameters() if p.trainable]
        for p in params:
            shadow = create_global_var(
                list(p.shape), 0.0, p.dtype, persistable=True,
                name=unique_name.generate(f"{p.name}_ema"))
            self._shadows[p.name] = shadow
            # shadow = decay*shadow + (1-decay)*param, as graph ops
            block.append_op(
                "scale", inputs={"X": [shadow.name]},
                outputs={"Out": [shadow.name]},
                attrs={"scale": self._decay}, infer_shape=False)
            tmp = block.create_var(
                name=unique_name.generate("ema_tmp"), shape=p.shape,
                dtype=p.dtype)
            block.append_op(
                "scale", inputs={"X": [p.name]},
                outputs={"Out": [tmp.name]},
                attrs={"scale": 1.0 - self._decay}, infer_shape=False)
            block.append_op(
                "elementwise_add", inputs={"X": [shadow.name],
                                           "Y": [tmp.name]},
                outputs={"Out": [shadow.name]}, infer_shape=False)

    def apply(self, executor, need_restore=True):
        from .core.scope import global_scope
        scope = global_scope()
        for pname, shadow in self._shadows.items():
            self._backups[pname] = scope.get(pname)
            scope.set(pname, scope.get(shadow.name))

    def restore(self, executor):
        from .core.scope import global_scope
        scope = global_scope()
        for pname, val in self._backups.items():
            scope.set(pname, val)
        self._backups.clear()


class ModelAverage(Optimizer):
    """Running average of params over a window (optimizer.py:2484)."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self._window = max_average_window
        self._sums = {}
        self._backups = {}

    def _attach(self, block, params):
        for p in params:
            if p.name in self._sums:
                continue
            s = create_global_var(
                list(p.shape), 0.0, p.dtype, persistable=True,
                name=unique_name.generate(f"{p.name}_avg_sum"))
            n = create_global_var(
                [1], 0.0, "float32", persistable=True,
                name=unique_name.generate(f"{p.name}_avg_n"))
            self._sums[p.name] = (s, n)
            block.append_op("elementwise_add",
                            inputs={"X": [s.name], "Y": [p.name]},
                            outputs={"Out": [s.name]}, infer_shape=False)
            block.append_op("increment", inputs={"X": [n.name]},
                            outputs={"Out": [n.name]},
                            attrs={"step": 1.0}, infer_shape=False)

    def attach(self, program=None):
        prog = program or default_main_program()
        block = prog.current_block()
        self._attach(block, [p for p in prog.all_parameters()
                             if p.trainable])

    def apply(self, executor, need_restore=True):
        import numpy as np
        from .core.scope import global_scope
        scope = global_scope()
        for pname, (s, n) in self._sums.items():
            self._backups[pname] = scope.get(pname)
            total = np.asarray(scope.get(s.name))
            cnt = float(np.asarray(scope.get(n.name)).reshape(-1)[0])
            if cnt > 0:
                scope.set(pname, total / cnt)

    def restore(self, executor):
        from .core.scope import global_scope
        scope = global_scope()
        for pname, val in self._backups.items():
            scope.set(pname, val)
        self._backups.clear()


class LookaheadOptimizer:
    """k-step lookahead wrapper (optimizer.py:3606): every k steps the slow
    weights pull toward the fast weights and the fast weights reset to the
    slow weights. Branch-free: sync_mask = 1[step % k == 0] gates both
    updates inside the one compiled step (XLA-friendly, no conditional
    blocks — contrast the reference's Switch-based program rewrite)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert 0.0 <= alpha <= 1.0 and k >= 1
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        from .framework import default_startup_program
        opt_ops, params_grads = self.inner.minimize(loss, startup_program)
        block = default_main_program().current_block()
        from .layers.learning_rate_scheduler import \
            autoincreased_step_counter
        from .layers.tensor import cast
        step = autoincreased_step_counter(counter_name="@LOOKAHEAD_STEP@")
        fstep = cast(step, "float32")
        # frac = step/k - floor(step/k); sync_mask = 1 - sign(frac)
        from .layers.nn import sign
        inv_k = fstep * (1.0 / self.k)
        floor_v = block.create_var(name=unique_name.generate("la_floor"),
                                   shape=(1,), dtype="float32")
        block.append_op("floor", inputs={"X": [inv_k.name]},
                        outputs={"Out": [floor_v.name]}, infer_shape=False)
        frac = inv_k - block.var(floor_v.name)
        mask = sign(frac) * -1.0 + 1.0  # [1] -> 1.0 at sync steps else 0.0
        sp = (startup_program or default_startup_program()).global_block()
        for p, _ in params_grads:
            slow = create_global_var(
                list(p.shape), 0.0, p.dtype, persistable=True,
                name=unique_name.generate(f"{p.name}_slow"))
            # slow starts equal to the param (after its init op runs)
            sp.append_op("assign", inputs={"X": [p.name]},
                         outputs={"Out": [slow.name]}, infer_shape=False)
            # new_slow = slow + mask*alpha*(fast - slow); fast = mask
            # selects new_slow else keeps fast.
            tmp = block.create_var(name=unique_name.generate("la_tmp"),
                                   shape=p.shape, dtype=p.dtype)
            block.append_op("elementwise_sub",
                            inputs={"X": [p.name], "Y": [slow.name]},
                            outputs={"Out": [tmp.name]}, infer_shape=False)
            block.append_op("scale", inputs={"X": [tmp.name]},
                            outputs={"Out": [tmp.name]},
                            attrs={"scale": self.alpha}, infer_shape=False)
            block.append_op("elementwise_mul",
                            inputs={"X": [tmp.name], "Y": [mask.name]},
                            outputs={"Out": [tmp.name]},
                            attrs={"axis": 0}, infer_shape=False)
            block.append_op("elementwise_add",
                            inputs={"X": [slow.name], "Y": [tmp.name]},
                            outputs={"Out": [slow.name]}, infer_shape=False)
            # fast = fast + mask*(slow - fast)
            diff = block.create_var(name=unique_name.generate("la_diff"),
                                    shape=p.shape, dtype=p.dtype)
            block.append_op("elementwise_sub",
                            inputs={"X": [slow.name], "Y": [p.name]},
                            outputs={"Out": [diff.name]}, infer_shape=False)
            block.append_op("elementwise_mul",
                            inputs={"X": [diff.name], "Y": [mask.name]},
                            outputs={"Out": [diff.name]},
                            attrs={"axis": 0}, infer_shape=False)
            block.append_op("elementwise_add",
                            inputs={"X": [p.name], "Y": [diff.name]},
                            outputs={"Out": [p.name]}, infer_shape=False)
        return opt_ops, params_grads


class RecomputeOptimizer:
    """Activation recomputation wrapper (reference optimizer.py:3313).

    The reference re-runs forward sub-segments in the backward pass
    (backward.py:576). Here minimize() first rewrites the forward into
    `recompute_segment` sub-blocks at the marked checkpoints
    (parallel/recompute.py); each segment lowers under jax.checkpoint, so
    the generic vjp backward recomputes it and XLA drops the internal
    activations from HBM.
    """

    def __init__(self, optimizer):
        self.inner = optimizer
        self._checkpoints = []

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, **kw):
        return self.inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        return self.inner.apply_gradients(params_grads)

    def load(self, state):
        raise NotImplementedError(
            "load() is unsupported (matches reference RecomputeOptimizer)")

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._checkpoints:
            from .parallel.recompute import rewrite_program_for_recompute
            rewrite_program_for_recompute(
                loss.block.program, self._checkpoints, keep_names=[loss])
        return self.inner.minimize(loss, startup_program, parameter_list,
                                   no_grad_set)


class GradientMergeOptimizer:
    """Gradient accumulation over k steps (reference multi_batch_merge_pass,
    ir/multi_batch_merge_pass.cc; fluid 1.6's GradientMergeOptimizer).

    Gradients accumulate into persistable buffers every step; every k-th
    step the inner optimizer's update ops run inside a conditional_block
    (lax.cond), so optimizer state (Adam moments etc.) mutates ONLY on
    apply steps — identical to running the optimizer on a k-times-larger
    batch.
    """

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self.inner.backward(loss, startup_program, parameter_list,
                                   no_grad_set, callbacks)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .layers import nn as nn_layers
        from .layers.control_flow import _CondBlockGuard
        from .layers.learning_rate_scheduler import every_n_steps

        params_grads = self.inner.backward(
            loss, startup_program, parameter_list, no_grad_set)
        if self.k_steps <= 1:
            return self.inner.apply_gradients(params_grads), params_grads

        block = default_main_program().current_block()
        cond = every_n_steps(
            self.k_steps,
            counter_name=unique_name.generate("@GRADIENT_MERGE_STEP@"))

        merged = []
        for p, g in params_grads:
            acc = create_global_var(
                list(p.shape), 0.0, p.dtype, persistable=True,
                name=unique_name.generate(f"{p.name}_gradient_merge"))
            block.append_op(  # in-place: acc += grad
                "elementwise_add", inputs={"X": [acc.name], "Y": [g.name]},
                outputs={"Out": [acc.name]}, attrs={"axis": -1},
                infer_shape=False)
            merged.append((p, acc))

        with _CondBlockGuard(cond):
            applied = []
            for p, acc in merged:
                eff = nn_layers.scale(acc, scale=1.0 / self.k_steps) \
                    if self.avg else acc
                applied.append((p, eff))
            opt_ops = self.inner.apply_gradients(applied)
            sub = default_main_program().current_block()
            for _, acc in merged:
                sub.append_op(  # reset buffer after apply
                    "scale", inputs={"X": [acc.name]},
                    outputs={"Out": [acc.name]},
                    attrs={"scale": 0.0, "bias": 0.0}, infer_shape=False)
        return opt_ops, params_grads


class PipelineOptimizer:
    """Pipeline-parallel sectioning (reference optimizer.py:3020).

    Implemented on TPU via the parallel.pipeline module (GPipe-style
    microbatch schedule with lax.scan); this wrapper records cut points.
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0):
        self.inner = optimizer
        self.cut_list = cut_list or []

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.inner.minimize(loss, startup_program, parameter_list,
                                   no_grad_set)


# fluid-style short aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
LarsMomentum = LarsMomentumOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
Dpsgd = DpsgdOptimizer
DGCMomentum = DGCMomentumOptimizer

"""LayerHelper: shared plumbing for layers.* graph builders.

Reference: python/paddle/fluid/layer_helper.py:42 — creates parameters in the
startup program (with their init ops) + the main program, appends compute ops
to the main program, applies default initializers / activations / bias.
"""
from __future__ import annotations

from .framework import (ParamAttr, default_main_program,
                        default_startup_program, unique_name)
from .initializer import Constant, Xavier

__all__ = ["LayerHelper"]

# Ops through which a sequence-lengths link propagates: anything that
# keeps the leading [batch, time] dims of its primary input. The link
# (program.lod_link) lets sequence layers find the ragged input's
# lengths var without the user threading it through every call —
# the build-time analogue of LoD metadata flowing through reference
# kernels (lod_tensor.h + each op's InferShape copying LoD).
_LOD_PRESERVING = {
    "lookup_table", "lookup_table_v2", "cast", "scale", "dropout",
    "relu", "tanh", "sigmoid", "gelu", "leaky_relu", "elu", "selu",
    "softsign", "softplus", "swish", "hard_swish", "brelu", "abs",
    "square", "sqrt", "rsqrt", "exp", "log", "pow", "relu6", "clip",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "layer_norm", "softmax", "log_softmax",
    "sequence_softmax", "sequence_reverse", "emb_eltwise_layernorm",
    # recurrent ops keep [batch, time] (their Lengths input already
    # masks the padded tail); dynamic_lstmp also emits op type "lstm"
    "lstm", "gru",
}
# aux output slots that never carry sequence data
_LOD_AUX_SLOTS = {"Mask", "MaxIndex", "Mean", "Variance", "SavedMean",
                  "SavedVariance", "XShape", "MeanOut", "VarianceOut"}


def _propagate_lod_link(block, op_type, inputs, outputs, attrs):
    prog = block.program
    if not prog.lod_link:
        return
    # "mul" keeps [b, t] only when x is flattened after dim >= 2
    if op_type == "mul":
        if (attrs or {}).get("x_num_col_dims", 1) < 2:
            return
    elif op_type == "concat":
        # feature-axis concat keeps [b, t]; batch/time concat does not
        if (attrs or {}).get("axis", 0) in (0, 1):
            return
    elif op_type not in _LOD_PRESERVING:
        return
    src = None
    for slot, names in (inputs or {}).items():
        for n in names or []:
            if n in prog.lod_link:
                src = prog.lod_link[n]
                break
        if src:
            break
    if not src:
        return
    for slot, names in (outputs or {}).items():
        if slot in _LOD_AUX_SLOTS:
            continue
        for n in names or []:
            prog.lod_link.setdefault(n, src)


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name or unique_name.generate(layer_type)

    @property
    def main_program(self):
        return self.kwargs.get("main_program") or default_main_program()

    @property
    def startup_program(self):
        return self.kwargs.get("startup_program") or \
            default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        name = attr.name or unique_name.generate(
            f"{self.name}.{'b' if is_bias else 'w'}")
        init = attr.initializer or default_initializer or \
            (Constant(0.0) if is_bias else Xavier())
        shape = [int(s) for s in shape]
        # Parameter lives in BOTH programs: startup (with its init op) and
        # main (as an input to compute ops) — mirroring fluid's
        # global_block duplication (framework.py Parameter creation).
        sp = self.startup_program.global_block()
        sv = sp.create_parameter(name, shape, dtype, trainable=attr.trainable)
        init(sv, sp)
        p = self.block.program.global_block().create_parameter(
            name, shape, dtype, trainable=attr.trainable,
            regularizer=attr.regularizer,
            optimize_attr={"learning_rate": attr.learning_rate},
            do_model_average=attr.do_model_average)
        return p

    def create_variable_for_type_inference(self, dtype="float32",
                                           stop_gradient=False):
        from . import dygraph
        if dygraph.enabled():
            return dygraph.VarBase(None, stop_gradient=stop_gradient)
        return self.block.create_var(
            name=unique_name.generate(f"{self.name}.tmp"),
            dtype=dtype, stop_gradient=stop_gradient)

    create_tmp_variable = create_variable_for_type_inference

    def append_op(self, **kwargs):
        from . import dygraph
        if dygraph.enabled():
            # layers.* in dygraph mode: resolve name-keyed slots to live
            # eager vars and dispatch to the tracer (the reference routes
            # LayerHelper through Tracer::TraceOp the same way,
            # dygraph layer_object_helper).
            vm = dygraph._state["var_map"]

            def resolve(slot_map):
                out = {}
                for slot, items in (slot_map or {}).items():
                    vs = []
                    for it in items or []:
                        if isinstance(it, dygraph.VarBase):
                            vs.append(it)
                        elif it in vm:
                            vs.append(vm[it])
                        else:
                            raise KeyError(
                                f"dygraph var {it!r} not found for "
                                f"{kwargs['type']}.{slot}")
                    out[slot] = vs
                return out

            return dygraph.trace_op(kwargs["type"],
                                    resolve(kwargs.get("inputs")),
                                    kwargs.get("attrs") or {},
                                    out_vars=resolve(kwargs.get("outputs")))
        _propagate_lod_link(self.block, kwargs["type"],
                            kwargs.get("inputs"), kwargs.get("outputs"),
                            kwargs.get("attrs"))
        return self.block.append_op(
            kwargs["type"], inputs=kwargs.get("inputs"),
            outputs=kwargs.get("outputs"), attrs=kwargs.get("attrs"))

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type="elementwise_add",
                       inputs={"X": [input_var.name], "Y": [b.name]},
                       outputs={"Out": [out.name]},
                       attrs={"axis": dim_start})
        return out

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, dict):
            act_type = act.pop("type")
            act_attrs = act
        else:
            act_type, act_attrs = act, {}
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type=act_type, inputs={"X": [input_var.name]},
                       outputs={"Out": [out.name]}, attrs=act_attrs)
        return out

    def input(self, name):
        return self.kwargs[name]

"""Host-side RPC for parameter-server training.

Reference: operators/distributed/ — `RPCClient`/`RPCServer` (rpc_client.h:34,
rpc_server.h:48) over gRPC/BRPC with protobuf-framed tensors
(sendrecvop_utils.cc, send_recv.proto.in). The TPU rebuild keeps the PS
topology host-side (SURVEY.md §2.8: the RPC stack maps to DCN/host gRPC);
this module is a dependency-free equivalent: length-prefixed JSON header +
raw ndarray payload over TCP, persistent connection per trainer, threaded
server.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["RPCClient", "RPCServer", "send_msg", "recv_msg"]


def _recvn(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def send_msg(sock: socket.socket, header: dict, payload: bytes = b""):
    h = json.dumps(header).encode()
    sock.sendall(struct.pack("<II", len(h), len(payload)) + h + payload)


def recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    hlen, plen = struct.unpack("<II", _recvn(sock, 8))
    header = json.loads(_recvn(sock, hlen).decode())
    payload = _recvn(sock, plen) if plen else b""
    return header, payload


def pack_array(arr: np.ndarray) -> Tuple[dict, bytes]:
    arr = np.ascontiguousarray(arr)
    return ({"dtype": str(arr.dtype), "shape": list(arr.shape)},
            arr.tobytes())


def unpack_array(meta: dict, payload: bytes) -> np.ndarray:
    return np.frombuffer(payload, dtype=np.dtype(meta["dtype"])).reshape(
        meta["shape"]).copy()


class RPCServer:
    """Threaded request server: handler(header, payload) -> (header, payload).

    The handler may block (sync-mode barrier semantics live in the
    handler, mirroring listen_and_serv's batch barriers, rpc_server.h:48).
    """

    def __init__(self, endpoint: str,
                 handler: Callable[[dict, bytes], Tuple[dict, bytes]]):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.endpoint = f"{host}:{self._sock.getsockname()[1]}"
        self._handler = handler
        self._running = False

    def start(self):
        self._running = True
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while True:
                header, payload = recv_msg(conn)
                out_h, out_p = self._handler(header, payload)
                send_msg(conn, out_h, out_p)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


class RPCClient:
    """Persistent-connection client (reference grpc_client.h:190
    AsyncSendVar/AsyncGetVar — here calls are synchronous; the executor's
    ordered host callbacks serialize them anyway)."""

    _lock = threading.Lock()
    _instances: Dict[int, "RPCClient"] = {}

    def __init__(self, trainer_id: int = 0):
        self.trainer_id = trainer_id
        self._conns: Dict[str, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._ep_locks: Dict[str, threading.Lock] = {}

    @classmethod
    def instance(cls, trainer_id: int = 0) -> "RPCClient":
        with cls._lock:
            if trainer_id not in cls._instances:
                cls._instances[trainer_id] = cls(trainer_id)
            return cls._instances[trainer_id]

    def _conn(self, endpoint: str) -> socket.socket:
        with self._conn_lock:
            if endpoint not in self._conns:
                host, port = endpoint.rsplit(":", 1)
                s = socket.create_connection((host, int(port)), timeout=120)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns[endpoint] = s
                self._ep_locks[endpoint] = threading.Lock()
            return self._conns[endpoint]

    def _call(self, endpoint: str, header: dict,
              payload: bytes = b"") -> Tuple[dict, bytes]:
        header = dict(header, trainer_id=self.trainer_id)
        conn = self._conn(endpoint)
        # one in-flight request per connection: a request/response pair must
        # not interleave with another thread's on the same socket
        with self._ep_locks[endpoint]:
            send_msg(conn, header, payload)
            return recv_msg(conn)

    # -- verbs (reference rpc_client.h) --------------------------------
    def send_var(self, endpoint: str, name: str, arr: np.ndarray):
        meta, payload = pack_array(np.asarray(arr))
        h, _ = self._call(endpoint, {"method": "send_var", "name": name,
                                     **meta}, payload)
        if h.get("status") != "ok":
            raise RuntimeError(f"send_var({name}) -> {h}")

    def get_var(self, endpoint: str, name: str) -> np.ndarray:
        h, p = self._call(endpoint, {"method": "get_var", "name": name})
        if h.get("status") != "ok":
            raise RuntimeError(f"get_var({name}) -> {h}")
        return unpack_array(h, p)

    def send_barrier(self, endpoint: str):
        self._call(endpoint, {"method": "send_barrier"})

    def fetch_barrier(self, endpoint: str):
        self._call(endpoint, {"method": "fetch_barrier"})

    def send_complete(self, endpoint: str):
        try:
            self._call(endpoint, {"method": "complete"})
        except (ConnectionError, OSError):
            pass

    def ping(self, endpoint: str):
        self._call(endpoint, {"method": "ping"})

    def geo_push_pull(self, endpoint: str, name: str,
                      delta: np.ndarray) -> np.ndarray:
        meta, payload = pack_array(np.asarray(delta))
        h, p = self._call(endpoint, {"method": "geo_push_pull",
                                     "name": name, **meta}, payload)
        if h.get("status") != "ok":
            raise RuntimeError(f"geo_push_pull({name}) -> {h}")
        return unpack_array(h, p)

    def close(self):
        with self._conn_lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()

    @classmethod
    def reset_all(cls):
        with cls._lock:
            for c in cls._instances.values():
                c.close()
            cls._instances.clear()

"""Host-sharded sparse embedding tables over the PS RPC layer.

Reference: large-scale sparse training — SelectedRows embeddings pulled/
pushed row-wise through the parameter server (distributed_lookup_table_op,
operators/distributed/parameter_prefetch.cc, DownpourWorker pull/push
sparse, fleet_wrapper.h:55). SURVEY §7.10 names this the TPU answer to
vocab tables too big for one chip: the dense model trains on device, the
embedding rows live host-side, sharded across pservers by id (HashName
dispatch, ps_dispatcher.py), crossing only as the few rows a batch
touches.

Server side: SparseTableServer holds {table: rows} shards, serves
sparse_pull (lazy zero-or-seeded init per row) and sparse_push (row SGD).
PServerRuntime embeds the same handlers so a transpiled PS job can carry
sparse tables alongside dense params.

Client side: SparseTableClient shards ids by `id % n_endpoints`, pulls
rows, scatters them back into batch order; push reverses it.
"""
from __future__ import annotations

import threading
from typing import Dict, List

import numpy as np

from .rpc import RPCClient, RPCServer

__all__ = ["SparseTableShard", "SparseTableServer", "SparseTableClient"]


class SparseTableShard:
    """One server's shard of one table: rows materialized on first touch
    (the reference's lazy per-key init in the PS)."""

    def __init__(self, dim, init_std=0.01, seed=0, lr=0.1):
        self.dim = int(dim)
        self.init_std = float(init_std)
        self.lr = float(lr)
        self._rows: Dict[int, np.ndarray] = {}
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def pull(self, ids: np.ndarray) -> np.ndarray:
        out = np.empty((len(ids), self.dim), np.float32)
        with self._lock:
            for i, key in enumerate(np.asarray(ids, np.int64)):
                row = self._rows.get(int(key))
                if row is None:
                    row = (self._rng.normal(0, self.init_std, self.dim)
                           .astype(np.float32))
                    self._rows[int(key)] = row
                out[i] = row
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray, lr=None):
        lr = self.lr if lr is None else float(lr)
        with self._lock:
            for key, g in zip(np.asarray(ids, np.int64),
                              np.asarray(grads, np.float32)):
                row = self._rows.get(int(key))
                if row is None:
                    row = np.zeros(self.dim, np.float32)
                self._rows[int(key)] = row - lr * g

    def __len__(self):
        return len(self._rows)


def _handle_sparse(tables, header, payload, make_shard):
    """Shared pull/push handler (used by SparseTableServer and embedded
    in PServerRuntime)."""
    from .rpc import pack_array, unpack_array
    method = header.get("method")
    if method == "sparse_pull":
        name = header["name"]
        shard = tables.get(name)
        if shard is None:
            shard = tables.setdefault(name, make_shard(header))
        ids = unpack_array(header, payload)
        rows = shard.pull(ids.reshape(-1))
        meta, body = pack_array(rows)
        return {"status": "ok", **meta}, body
    if method == "sparse_push":
        name = header["name"]
        shard = tables.get(name)
        if shard is None:
            shard = tables.setdefault(name, make_shard(header))
        n_ids = int(header["n_ids"])
        ids = np.frombuffer(payload[:8 * n_ids], np.int64)
        grads = np.frombuffer(payload[8 * n_ids:], np.float32) \
            .reshape(len(ids), shard.dim)
        shard.push(ids, grads, lr=header.get("lr"))
        return {"status": "ok"}, b""
    return None


def _make_shard_from_header(header):
    return SparseTableShard(dim=int(header.get("dim", 1)),
                            init_std=float(header.get("init_std", 0.01)),
                            seed=int(header.get("seed", 0)),
                            lr=float(header.get("lr", 0.1) or 0.1))


class SparseTableServer:
    """Standalone sparse-table PS (one shard server)."""

    def __init__(self, endpoint="127.0.0.1:0"):
        self.tables: Dict[str, SparseTableShard] = {}
        self._server = RPCServer(endpoint, self._handle)
        self.endpoint = self._server.endpoint

    def _handle(self, header, payload):
        r = _handle_sparse(self.tables, header, payload,
                           _make_shard_from_header)
        if r is not None:
            return r
        if header.get("method") == "ping":
            return {"status": "ok"}, b""
        return {"status": f"unknown method {header.get('method')!r}"}, b""

    def start(self):
        self._server.start()
        return self

    def stop(self):
        self._server.stop()


class SparseTableClient:
    """Trainer-side view of a table sharded across endpoints by
    `id % n_endpoints` (HashName dispatch, ps_dispatcher.py)."""

    def __init__(self, table_name: str, endpoints: List[str], dim: int,
                 trainer_id=0, lr=0.1, init_std=0.01, seed=0):
        self.name = table_name
        self.endpoints = list(endpoints)
        self.dim = int(dim)
        self.lr = float(lr)
        self.init_std = float(init_std)
        self.seed = int(seed)
        self._client = RPCClient.instance(trainer_id)

    def _meta(self):
        return {"name": self.name, "dim": self.dim, "lr": self.lr,
                "init_std": self.init_std, "seed": self.seed}

    def _shard_ids(self, flat_ids):
        n = len(self.endpoints)
        owner = flat_ids % n
        return [(ep_i, np.where(owner == ep_i)[0])
                for ep_i in range(n)]

    def pull(self, ids: np.ndarray) -> np.ndarray:
        from .rpc import pack_array, unpack_array
        flat = np.asarray(ids, np.int64).reshape(-1)
        out = np.empty((flat.size, self.dim), np.float32)
        for ep_i, pos in self._shard_ids(flat):
            if not pos.size:
                continue
            meta, body = pack_array(flat[pos])
            h, p = self._client._call(
                self.endpoints[ep_i],
                {"method": "sparse_pull", **self._meta(), **meta}, body)
            if h.get("status") != "ok":
                raise RuntimeError(f"sparse_pull -> {h}")
            out[pos] = unpack_array(h, p)
        return out.reshape(tuple(np.asarray(ids).shape) + (self.dim,))

    def push(self, ids: np.ndarray, grads: np.ndarray):
        flat = np.asarray(ids, np.int64).reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, self.dim)
        for ep_i, pos in self._shard_ids(flat):
            if not pos.size:
                continue
            payload = flat[pos].tobytes() + \
                np.ascontiguousarray(g[pos]).tobytes()
            h, _ = self._client._call(
                self.endpoints[ep_i],
                {"method": "sparse_push", **self._meta(),
                 "n_ids": int(pos.size)}, payload)
            if h.get("status") != "ok":
                raise RuntimeError(f"sparse_push -> {h}")

"""Parameter-server runtime: the listen_and_serv loop.

Reference: operators/distributed_ops/listen_and_serv_op.cc — the pserver
executes an RPC service that (sync mode) waits on a batch barrier for all
trainers' grads, runs one optimizer sub-block per param, then serves the
updated params; async mode applies each grad on arrival
(AsyncCommunicator, communicator.h:288). Worker liveness follows
HeartBeatMonitor (heart_beat_monitor.h:54,104).

Here the optimizer sub-blocks still lower to XLA (each param's update is
one tiny jitted program, compiled once); only the RPC+barrier choreography
is host-side Python, mirroring how the reference keeps the PS control
plane on the host while kernels run on device.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from .rpc import RPCServer, pack_array, unpack_array

__all__ = ["PServerRuntime", "HeartBeatMonitor", "run_pserver"]


class HeartBeatMonitor:
    """Chief-pserver worker-liveness tracker (heart_beat_monitor.h:54).

    Workers are marked lost when silent for `timeout` seconds; COMPLETED
    workers are exempt (LostWorkerMonitor :104).
    """

    def __init__(self, n_workers: int, timeout: float = 60.0):
        self.timeout = timeout
        # never-connected workers count from monitor start, so a trainer
        # that fails to launch is still detected as lost
        self._start = time.monotonic()
        self._last_seen: Dict[int, float] = {}
        self._completed = set()
        self._lock = threading.Lock()
        self.n_workers = n_workers

    def update(self, worker_id: int, status: str = "PING"):
        with self._lock:
            if status == "COMPLETED":
                self._completed.add(worker_id)
            self._last_seen[worker_id] = time.monotonic()

    def lost_workers(self):
        now = time.monotonic()
        with self._lock:
            lost = {w for w, t in self._last_seen.items()
                    if w not in self._completed and now - t > self.timeout}
            if now - self._start > self.timeout:
                lost.update(w for w in range(self.n_workers)
                            if w not in self._last_seen
                            and w not in self._completed)
            return sorted(lost)


class PServerRuntime:
    """Executes a pserver program produced by DistributeTranspiler."""

    def __init__(self, pserver_program, startup_program=None, scope=None,
                 heartbeat_timeout: float = 60.0):
        from ..core.scope import Scope
        from ..executor import Executor

        ls = next(op for op in pserver_program.global_block().ops
                  if op.type in ("listen_and_serv", "fl_listen_and_serv"))
        self.program = pserver_program
        self._notifications = []  # distributed_notify records
        self._sparse_tables = {}  # host-sharded embedding shards
        self.params = list(ls.attrs["params"])
        self.grad_of_param = dict(ls.attrs["grad_of_param"])
        self.opt_block_of = dict(ls.attrs["opt_block_of"])
        self.sync_mode = ls.attrs.get("sync_mode", True)
        self.fanin = int(ls.attrs.get("Fanin", 1))
        self.endpoint = ls.attrs["endpoint"]

        self.scope = scope if scope is not None else Scope()
        self.exe = Executor()
        if startup_program is not None:
            self.exe.run(startup_program, scope=self.scope)

        # per-param optimizer programs (sub-block -> standalone Program)
        self._opt_progs = {p: self._opt_program(p) for p in self.params}
        # lr-scheduler program: runs once per batch before the updates
        lr_idx = ls.attrs.get("lr_block", -1)
        self._lr_prog = self._block_program(lr_idx) if lr_idx >= 0 else None

        self.monitor = HeartBeatMonitor(self.fanin, heartbeat_timeout)
        self._lock = threading.Lock()
        self._batch_cv = threading.Condition(self._lock)
        self._grad_buf: Dict[str, list] = {p: [] for p in self.params}
        self._async_seen = 0  # async mode: grads since last lr tick
        self._barrier_count = 0
        self._batch_id = 0
        self._applied_batch = 0
        self._completed = set()
        self._server = RPCServer(self.endpoint, self._handle)
        self.endpoint = self._server.endpoint  # resolved port (":0" ok)

    # ------------------------------------------------------------------
    def _block_program(self, block_idx):
        """Sub-block of the pserver program -> standalone Program
        (op ids preserved: lr ops' PRNG/step determinism)."""
        from ..framework import Operator, Program

        src = self.program
        sub = src.blocks[block_idx]
        prog = Program()
        prog.random_seed = src.random_seed
        blk = prog.global_block()
        src_g = src.global_block()
        for op in sub.ops:
            for n in list(op.input_names()) + list(op.output_names()):
                if n and not blk.has_var(n) and src_g.has_var(n):
                    v = src_g.var(n)
                    blk.create_var(name=n, shape=v.shape, dtype=v.dtype,
                                   persistable=True, stop_gradient=True)
            new_op = Operator(blk, op.type, op.inputs, op.outputs,
                              op.attrs, op_id=op.id)
            blk.ops.append(new_op)
        prog._fp_cache = None
        return prog

    def _opt_program(self, param):
        return self._block_program(self.opt_block_of[param])

    # ------------------------------------------------------------------
    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop()

    def wait_all_completed(self, timeout: Optional[float] = None):
        """Block until every trainer sent 'complete'. timeout=None blocks
        indefinitely (reference listen_and_serv semantics)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._batch_cv:
            while len(self._completed) < self.fanin:
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"pserver {self.endpoint}: only "
                        f"{len(self._completed)}/{self.fanin} trainers "
                        f"completed")
                wait_t = 1.0 if deadline is None else \
                    min(1.0, max(0.0, deadline - time.monotonic()))
                self._batch_cv.wait(timeout=wait_t)

    # ------------------------------------------------------------------
    def _live_count(self) -> int:
        """Trainers still expected at the barrier: fanin minus completed
        minus heartbeat-lost."""
        lost = set(self.monitor.lost_workers())
        return self.fanin - len(self._completed | lost)

    def _apply_param(self, param, grads):
        g_name = self.grad_of_param[param]
        merged = np.mean(grads, axis=0) if len(grads) > 1 else grads[0]
        self.scope.set(g_name, merged)
        self.exe.run(self._opt_progs[param], scope=self.scope)

    def _apply_batch_locked(self):
        if self._lr_prog is not None:
            self.exe.run(self._lr_prog, scope=self.scope)
        for p in self.params:
            buf = self._grad_buf[p]
            if buf:
                self._apply_param(p, buf)
                self._grad_buf[p] = []
        self._applied_batch = self._batch_id
        self._batch_id += 1
        self._barrier_count = 0
        self._batch_cv.notify_all()

    # ------------------------------------------------------------------
    def _handle(self, header, payload):
        method = header.get("method")
        tid = int(header.get("trainer_id", 0))
        self.monitor.update(tid, "PING")

        if method == "send_var":
            name = header["name"]
            arr = unpack_array(header, payload)
            param = next((p for p, g in self.grad_of_param.items()
                          if g == name), None)
            if param is None:
                return {"status": f"unknown grad {name!r}"}, b""
            with self._batch_cv:
                if self.sync_mode:
                    self._grad_buf[param].append(arr)
                else:
                    # lr schedule ticks once per FULL grad round, not once
                    # per param (distribute_transpiler invariant)
                    if self._lr_prog is not None and \
                            self._async_seen % max(1, len(self.params)) == 0:
                        self.exe.run(self._lr_prog, scope=self.scope)
                    self._async_seen += 1
                    self._apply_param(param, [arr])
            return {"status": "ok"}, b""

        if method == "send_barrier":
            with self._batch_cv:
                if self.sync_mode:
                    self._barrier_count += 1
                    if self._barrier_count >= max(1, self._live_count()):
                        self._apply_batch_locked()
                    else:
                        batch = self._batch_id
                        # wake periodically to re-check liveness: if a
                        # trainer died (HeartBeatMonitor), the survivors'
                        # barrier must not deadlock (heart_beat_monitor.h
                        # LostWorkerMonitor:104 motivates exactly this)
                        while not (self._batch_id > batch
                                   or len(self._completed) >= self.fanin):
                            self._batch_cv.wait(timeout=1.0)
                            if self._batch_id > batch:
                                break
                            if self._barrier_count >= max(
                                    1, self._live_count()):
                                self._apply_batch_locked()
                                break
            return {"status": "ok"}, b""

        if method == "get_var":
            name = header["name"]
            if not self.scope.has(name):
                return {"status": f"unknown var {name!r}"}, b""
            meta, data = pack_array(np.asarray(self.scope.get(name)))
            return {"status": "ok", **meta}, data

        if method == "fetch_barrier":
            return {"status": "ok"}, b""

        if method == "geo_push_pull":
            name = header["name"]
            delta = unpack_array(header, payload)
            with self._batch_cv:
                if not self.scope.has(name):
                    return {"status": f"unknown var {name!r}"}, b""
                cur = np.asarray(self.scope.get(name))
                self.scope.set(name, cur + delta)
            meta, data = pack_array(np.asarray(self.scope.get(name)))
            return {"status": "ok", **meta}, data

        if method == "complete":
            with self._batch_cv:
                self._completed.add(tid)
                self.monitor.update(tid, "COMPLETED")
                if self.sync_mode and self._barrier_count >= max(
                        1, self._live_count()):
                    self._apply_batch_locked()
                self._batch_cv.notify_all()
            return {"status": "ok"}, b""

        if method == "ping":
            return {"status": "ok"}, b""

        if method in ("sparse_pull", "sparse_push"):
            from .sparse_table import (_handle_sparse,
                                       _make_shard_from_header)
            r = _handle_sparse(self._sparse_tables, header, payload,
                               _make_shard_from_header)
            if r is not None:
                return r

        if method == "notify":
            # distributed_notify_op: record + ack; SAVE-type notifies
            # snapshot the server's persistable state like
            # checkpoint_notify (checkpoint_notify_op.cc)
            ntype = header.get("type", "NOTIFY")
            self._notifications.append(ntype)
            if ntype.upper().startswith("SAVE"):
                import numpy as _np
                import os as _os
                d = header.get("dir", "pserver_ckpt")
                _os.makedirs(d, exist_ok=True)
                blob = {n: self.scope.get_numpy(n) for n in self.params
                        if self.scope.has(n)}
                # sparse embedding shards: ids + rows per table (the
                # largest state in a §7.10 job must not be dropped)
                for tname, shard in self._sparse_tables.items():
                    with shard._lock:
                        keys = _np.asarray(sorted(shard._rows),
                                           _np.int64)
                        rows = _np.stack(
                            [shard._rows[int(k)] for k in keys]) \
                            if len(keys) else \
                            _np.zeros((0, shard.dim), _np.float32)
                    blob[f"__sparse__{tname}__ids"] = keys
                    blob[f"__sparse__{tname}__rows"] = rows
                _np.savez(_os.path.join(
                    d, f"{self.endpoint.replace(':', '_')}.npz"), **blob)
            return {"status": "ok"}, b""

        return {"status": f"unknown method {method!r}"}, b""


def run_pserver(pserver_program, startup_program=None, scope=None,
                block: bool = True) -> PServerRuntime:
    """Executor entry for a program whose main block is listen_and_serv
    (reference: exe.run(pserver_program) blocks in the server loop)."""
    rt = PServerRuntime(pserver_program, startup_program, scope)
    rt.start()
    if block:
        rt.wait_all_completed()
        rt.stop()
    return rt

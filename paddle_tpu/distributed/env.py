"""Multi-host runtime bootstrap: PADDLE_* env contract -> JAX
distributed runtime.

Reference: the NCCL/gRPC bootstrap in operators/distributed +
ParallelExecutor's multi-node graph (SURVEY.md §2.8). TPU-native
equivalent: one process per HOST (the launcher's worker = host model),
`jax.distributed.initialize` wires every host's chips into one global
device set, and GSPMD then lays collectives over ICI within a slice and
DCN across slices — no NCCL ring construction, no send/recv ops.

Typical use, mirroring fleet collective training:

    import paddle_tpu.distributed as dist
    dist.init_parallel_env()          # reads PADDLE_TRAINER_* env
    mesh = dist.global_mesh({"dp": -1, "tp": 8})
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["init_parallel_env", "global_mesh", "parallel_env_rank",
           "parallel_env_world_size"]

_init_args = None  # (coordinator, num_processes, process_id) after init


def parallel_env_rank() -> int:
    if _init_args is not None:
        return _init_args[2]
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def parallel_env_world_size() -> int:
    if _init_args is not None:
        return _init_args[1]
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Connect this process to the job's global JAX runtime.

    Defaults come from the launcher's env contract
    (PADDLE_TRAINER_ENDPOINTS / PADDLE_TRAINERS_NUM /
    PADDLE_TRAINER_ID): the coordinator is trainer 0's endpoint.
    Single-process jobs (world size 1) skip the distributed runtime
    entirely — jax.devices() is already correct.
    """
    global _init_args
    import jax
    n = num_processes if num_processes is not None else \
        int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n <= 1:
        # single-process: jax.devices() is already the whole job. Not
        # recorded as initialized — a later call with real multi-process
        # arguments must still work.
        return
    pid = process_id if process_id is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coordinator_address is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        if not eps:
            raise RuntimeError(
                "init_parallel_env: PADDLE_TRAINER_ENDPOINTS is not set "
                "and no coordinator_address was given — run under "
                "python -m paddle_tpu.distributed.launch or pass the "
                "coordinator explicitly")
        coordinator_address = eps.split(",")[0]
    if _init_args is not None:
        if _init_args != (coordinator_address, n, pid):
            raise RuntimeError(
                f"init_parallel_env: runtime already initialized as "
                f"{_init_args}, cannot re-initialize as "
                f"{(coordinator_address, n, pid)}")
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=n, process_id=pid)
    _init_args = (coordinator_address, n, pid)


def global_mesh(axes, devices=None):
    """Build a jax.sharding.Mesh over ALL job devices (every host's
    chips after init_parallel_env). `axes` is an ordered {name: size}
    dict; one size may be -1 (inferred). Axis order should put the
    fastest-communicating axes last so they map to ICI neighbors."""
    import jax
    from jax.sharding import Mesh
    devs = np.asarray(devices if devices is not None else jax.devices())
    sizes = list(axes.values())
    n_infer = sum(1 for s in sizes if s == -1)
    if n_infer > 1:
        raise ValueError("global_mesh: at most one axis size may be -1")
    known = int(np.prod([s for s in sizes if s != -1])) or 1
    if n_infer:
        if devs.size % known:
            raise ValueError(
                f"global_mesh: {devs.size} devices not divisible by "
                f"{known}")
        sizes = [devs.size // known if s == -1 else s for s in sizes]
    if int(np.prod(sizes)) != devs.size:
        raise ValueError(
            f"global_mesh: axes {dict(zip(axes, sizes))} need "
            f"{int(np.prod(sizes))} devices, job has {devs.size}")
    return Mesh(devs.reshape(sizes), tuple(axes.keys()))

"""Host-side distributed runtime: RPC, parameter-server loop, launcher.

Reference scope: operators/distributed/ (10.3k LoC gRPC/BRPC runtime),
operators/distributed_ops/, python/paddle/distributed/launch.py —
re-expressed as a small host TCP-RPC layer (DCN path) around XLA-compiled
update programs; ICI-scale collectives live in paddle_tpu.parallel
instead (SURVEY.md §2.8).
"""
from .ps_server import HeartBeatMonitor, PServerRuntime, run_pserver  # noqa: F401
from .rpc import RPCClient, RPCServer  # noqa: F401
from .env import (init_parallel_env, global_mesh,  # noqa: F401
                  parallel_env_rank, parallel_env_world_size)

"""Multi-process job launcher.

Reference: python/paddle/distributed/launch.py — spawns one process per
device/worker on the node, wiring PADDLE_TRAINER_ID /
PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT env vars; launch_ps.py
adds PSERVER roles. Usage:

  python -m paddle_tpu.distributed.launch --worker_num 2 train.py args...
  python -m paddle_tpu.distributed.launch --server_num 2 --worker_num 2 \
      train_ps.py

On TPU one process drives all local chips (XLA owns intra-host
parallelism), so worker_num defaults to the host count (1), not the chip
count — the key contrast with the reference's process-per-GPU model.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys

__all__ = ["launch"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--cluster_node_ips", default="127.0.0.1")
    p.add_argument("--node_ip", default="127.0.0.1")
    p.add_argument("--started_port", type=int, default=0,
                   help="0 = pick free ports")
    p.add_argument("--worker_num", "--nproc_per_node", type=int, default=1)
    p.add_argument("--server_num", type=int, default=0,
                   help=">0 starts parameter-server mode")
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _endpoints(ip, n, started_port):
    ports = ([started_port + i for i in range(n)] if started_port
             else [_free_port() for _ in range(n)])
    return [f"{ip}:{p}" for p in ports]


def launch(argv=None):
    args = _parse_args(argv)
    server_eps = _endpoints(args.node_ip, args.server_num,
                            args.started_port)
    worker_eps = _endpoints(
        args.node_ip, args.worker_num,
        args.started_port + args.server_num if args.started_port else 0)

    procs = []
    log_fhs = []

    def _spawn(env_extra, tag):
        env = dict(os.environ, **{k: str(v) for k, v in env_extra.items()})
        cmd = [sys.executable, args.training_script,
               *args.training_script_args]
        out = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            out = open(os.path.join(args.log_dir, f"{tag}.log"), "w")
            log_fhs.append(out)
        procs.append(subprocess.Popen(cmd, env=env, stdout=out,
                                      stderr=subprocess.STDOUT))

    common = {
        "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(server_eps),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(worker_eps),
        "PADDLE_TRAINERS_NUM": args.worker_num,
    }
    for i, ep in enumerate(server_eps):
        _spawn({**common, "TRAINING_ROLE": "PSERVER",
                "PADDLE_CURRENT_ENDPOINT": ep, "PADDLE_PORT":
                ep.rsplit(":", 1)[1]}, f"serverlog.{i}")
    for i, ep in enumerate(worker_eps):
        _spawn({**common, "TRAINING_ROLE": "TRAINER",
                "PADDLE_TRAINER_ID": i,
                "PADDLE_CURRENT_ENDPOINT": ep}, f"workerlog.{i}")

    def _terminate(signum=None, frame=None):
        for p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGINT, _terminate)
    signal.signal(signal.SIGTERM, _terminate)

    rc = 0
    try:
        # workers decide job success; servers are killed at the end
        for p in procs[len(server_eps):]:
            p.wait()
            rc = rc or p.returncode
    finally:
        _terminate()
        for fh in log_fhs:
            fh.close()
    return rc


if __name__ == "__main__":
    sys.exit(launch())

"""DataLoader / PyReader: host input pipeline with prefetch.

Reference: python/paddle/fluid/reader.py (DataLoader.from_generator :73,
PyReader :569) over C++ LoDTensorBlockingQueue + double-buffered reader ops
(operators/reader/buffered_reader.cc). On TPU the analogue is a host-side
prefetch thread that stages numpy batches while the device computes —
device transfer happens inside the jitted step, overlapped by XLA's async
dispatch. A native C++ feeder (utils/native) accelerates decode when built.
"""
from __future__ import annotations

import queue
import threading
import time

from . import goodput as _goodput
from .monitor import STAT_ADD, STAT_OBSERVE, STAT_SET

__all__ = ["DataLoader", "PyReader"]


class _WorkerError:
    """Envelope carrying a prefetch-worker exception to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class _GeneratorLoader:
    def __init__(self, feed_list, capacity, iterable, return_list,
                 use_double_buffer=True):
        self.feed_list = feed_list
        self.capacity = capacity
        self.iterable = iterable
        self.return_list = return_list
        self._gen = None
        self._places = None

    # -- configuration ---------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        from .io import batch as batch_decorator
        return self.set_sample_list_generator(
            batch_decorator(reader, batch_size, drop_last), places)

    def set_sample_list_generator(self, reader, places=None):
        from .data_feeder import DataFeeder
        feeder = DataFeeder(self.feed_list)

        def gen():
            for sample_list in reader():
                yield feeder.feed(sample_list)

        self._gen = gen
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        def gen():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield {v.name: b for v, b in zip(self.feed_list, batch)}

        self._gen = gen
        self._places = places
        return self

    # -- iteration with prefetch ----------------------------------------
    def __iter__(self):
        from .core.flags import FLAGS
        from .resilience.faults import injector as _fault_injector
        q: "queue.Queue" = queue.Queue(
            maxsize=self.capacity or FLAGS.reader_queue_depth)
        sentinel = object()

        def worker():
            # a generator exception must surface on the training
            # thread, not vanish as a silently-truncated epoch
            try:
                for item in self._gen():
                    q.put(item)
            except BaseException as e:  # noqa: BLE001
                q.put(_WorkerError(e))
            finally:
                q.put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            # batch-wait time: how long the training thread stalls on
            # the prefetch queue — the reference's reader-queue starvation
            # signal (monitor stat reader.batch_wait_seconds). Queue depth
            # sampled after the get shows remaining prefetch headroom.
            t0 = time.perf_counter()
            item = q.get()
            STAT_SET("reader.queue_depth", q.qsize())
            if item is sentinel:
                break
            if isinstance(item, _WorkerError):
                raise item.exc
            inj = _fault_injector()
            if inj is not None:
                # an injected reader stall (slow_step:site=reader) models
                # a slow data source — it must land in the batch-wait
                # signal, so it sits inside the measured window
                inj.pre_step("reader")
            wait_s = time.perf_counter() - t0
            STAT_OBSERVE("reader.batch_wait_seconds", wait_s)
            # goodput input_wait attribution + starvation detector
            # (goodput.input_wait_ms / goodput.input_starved_steps);
            # no-op unless FLAGS_enable_goodput and a run is active
            _goodput.note_input_wait(wait_s)
            STAT_ADD("reader.batches")
            yield item

    def __call__(self):
        return iter(self)

    # PyReader-style start/reset are no-ops for the iterable loader.
    def start(self):
        pass

    def reset(self):
        pass


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=None,
                       use_double_buffer=True, iterable=True,
                       return_list=False):
        """capacity=None defers to FLAGS_reader_queue_depth at iteration
        time (reference default: 2)."""
        return _GeneratorLoader(feed_list or [], capacity, iterable,
                                return_list, use_double_buffer)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Iterate a Dataset (QueueDataset / InMemoryDataset over the
        native C++ data feed) as feed dicts — the reference's
        DatasetLoader (reader.py:1355) without the per-place split:
        one process drives all local chips, so each batch feeds the
        whole (possibly sharded) step."""
        return _DatasetLoader(dataset, drop_last)


class _DatasetLoader(_GeneratorLoader):
    """Stages Dataset batches through the same bounded prefetch queue
    as the generator loader, so file read + MultiSlot parse overlap
    with device compute instead of stalling the training thread."""

    def __init__(self, dataset, drop_last):
        super().__init__(feed_list=[], capacity=None, iterable=True,
                         return_list=False)
        self._gen = lambda: dataset.batches(drop_last=drop_last)


class PyReader(_GeneratorLoader):
    def __init__(self, feed_list=None, capacity=None,
                 use_double_buffer=True, iterable=True, return_list=False):
        super().__init__(feed_list or [], capacity, iterable, return_list,
                         use_double_buffer)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)

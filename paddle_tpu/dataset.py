"""Dataset: file-backed training data over the native C++ feed.

Reference analogue: python/paddle/fluid/dataset.py `DatasetFactory` /
`InMemoryDataset` / `QueueDataset` configuring the C++ DataFeed/Dataset
stack (framework/data_feed.h:222 MultiSlotDataFeed, data_set.h:92
LoadIntoMemory, :99 LocalShuffle), consumed by
`Executor.train_from_dataset` (executor.py:1098). Here the C++ side is
native/src/data_feed.cc: parse workers + windowed shuffle + batcher
feeding a bounded queue; the trainer loop stays host-side and drives the
jitted XLA step (the HogwildWorker thread pool collapses into XLA's own
parallelism on TPU).

When the native toolchain is unavailable, a pure-Python parser provides the
same semantics (slower; same file format).
"""
from __future__ import annotations

import numpy as np

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset"]


class DatasetBase:
    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._use_vars = []
        self._pipe_command = None  # accepted for API parity; not used
        self._shuffle = False
        self._seed = 0

    # -- reference API surface ------------------------------------------
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_use_var(self, var_list):
        self._use_vars = list(var_list)

    def set_pipe_command(self, cmd):
        self._pipe_command = cmd

    def _slots(self):
        slots = []
        for v in self._use_vars:
            np_dt = np.dtype("int64") if "int" in str(v.dtype) \
                else np.dtype("float32")
            dim = 1
            for d in v.shape:
                if d is not None and d > 0:
                    dim *= d
            slots.append((v.name, np_dt, dim))
        return slots

    def _make_feed(self, drop_last=True):
        from .native import AVAILABLE, NativeDataFeed
        if AVAILABLE:
            feed = NativeDataFeed(self._slots(), self._batch_size,
                                  capacity=8, drop_last=drop_last)
            feed.set_filelist(self._filelist)
            if self._shuffle:
                feed.set_shuffle(True, self._seed)
            feed.start(self._thread_num)
            return feed
        return _PyFeed(self._slots(), self._batch_size, self._filelist,
                       drop_last, self._shuffle, self._seed)

    def batches(self, drop_last=True):
        """Iterate {var_name: np.ndarray[batch, dim]} batches."""
        slots = self._slots()
        shapes = {}
        for v in self._use_vars:
            dims = [d for d in v.shape if d is not None and d > 0]
            shapes[v.name] = dims or [1]
        for batch in self._make_feed(drop_last):
            out = {}
            for name, _, _ in slots:
                arr = batch[name]
                out[name] = arr.reshape([arr.shape[0]] + shapes[name])
            yield out


class QueueDataset(DatasetBase):
    """Streams batches straight off files (data_set.h QueueDataset)."""


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (data_set.h:92 LoadIntoMemory,
    :99 LocalShuffle, :102 GlobalShuffle).

    Global shuffle redesign: the reference routes every sample through
    the pservers to land on a random worker. On TPU pods the filelist is
    on a shared filesystem, so the same result needs no traffic — every
    worker scans the full list, keeps samples whose (seeded) global
    permutation index maps to it, and serves them in permuted order.
    Each sample lands on exactly one worker, order is globally random,
    workers never exchange bytes."""

    def __init__(self):
        super().__init__()
        self._mem = None          # list of parsed samples
        self._order = None        # serving order (indices into _mem)

    def load_into_memory(self):
        feed = _PyFeed(self._slots(), self._batch_size, self._filelist,
                       drop_last=True, shuffle=False, seed=0)
        self._mem = list(feed._samples())
        self._order = np.arange(len(self._mem))

    def local_shuffle(self):
        self._shuffle = True
        if self._mem is not None:
            rng = np.random.RandomState(self._seed)
            self._order = rng.permutation(len(self._mem))

    def global_shuffle(self, fleet=None, thread_num=None,
                       filelist_shared=True):
        """filelist_shared=True (the reference's global-shuffle usage):
        every worker set the FULL filelist; the shared-seed permutation
        stride-partitions samples across workers. Set False when each
        worker's filelist is already a disjoint shard (the
        fleet.util.get_file_shard pattern) — then this degrades to a
        local shuffle, because stride-slicing a worker-local sample set
        would silently drop (n-1)/n of the data."""
        self._shuffle = True
        wid, nworkers = 0, 1
        if fleet is not None:
            wid = getattr(fleet, "worker_index", lambda: 0)()
            nworkers = getattr(fleet, "worker_num", lambda: 1)()
        if self._mem is None:
            self.load_into_memory()
        if not filelist_shared or nworkers <= 1:
            rng = np.random.RandomState(self._seed + 12345)
            self._order = rng.permutation(len(self._mem))
            return
        # identical permutation on every worker (shared seed), then each
        # worker keeps its stride-slice of the permuted order
        rng = np.random.RandomState(self._seed + 12345)
        perm = rng.permutation(len(self._mem))
        self._order = perm[wid::max(nworkers, 1)]

    def release_memory(self):
        self._mem = None
        self._order = None

    def set_fleet_send_batch_size(self, _n):
        pass  # no inter-worker sends in the shared-FS design

    def batches(self, drop_last=True):
        if self._mem is None:
            yield from super().batches(drop_last)
            return
        slots = self._slots()
        shapes = {}
        for v in self._use_vars:
            dims = [d for d in v.shape if d is not None and d > 0]
            shapes[v.name] = dims or [1]
        packer = _PyFeed(slots, self._batch_size, [], drop_last,
                         False, 0)
        buf = []
        for i in self._order:
            buf.append(self._mem[i])
            if len(buf) == self._batch_size:
                yield self._reshape(packer._pack(buf), slots, shapes)
                buf = []
        if buf and not drop_last:
            yield self._reshape(packer._pack(buf), slots, shapes)

    @staticmethod
    def _reshape(batch, slots, shapes):
        out = {}
        for name, _, _ in slots:
            arr = batch[name]
            out[name] = arr.reshape([arr.shape[0]] + shapes[name])
        return out


class DatasetFactory:
    """Reference: dataset.py DatasetFactory.create_dataset."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        return QueueDataset()


class _PyFeed:
    """Pure-Python MultiSlot parser fallback (same format/semantics)."""

    def __init__(self, slots, batch_size, files, drop_last, shuffle, seed):
        self.slots = slots
        self.batch_size = batch_size
        self.files = files
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed

    def _samples(self):
        rng = np.random.RandomState(self.seed)
        window = []
        win_cap = self.batch_size * 64 if self.shuffle else 0
        for path in self.files:
            with open(path) as f:
                for line in f:
                    toks = line.split()
                    if not toks:
                        continue
                    # malformed lines are skipped, matching the native
                    # parser's return-false-and-count behaviour
                    try:
                        vals, i = [], 0
                        for _, dt, _dim in self.slots:
                            n = int(toks[i])
                            i += 1
                            conv = int if dt == np.int64 else float
                            vals.append([conv(t) for t in toks[i:i + n]])
                            if len(vals[-1]) != n:
                                raise ValueError("short row")
                            i += n
                    except (ValueError, IndexError):
                        self.parse_errors = getattr(
                            self, "parse_errors", 0) + 1
                        continue
                    if self.shuffle:
                        window.append(vals)
                        if len(window) >= win_cap:
                            j = rng.randint(len(window))
                            window[j], window[-1] = window[-1], window[j]
                            yield window.pop()
                    else:
                        yield vals
        while window:
            yield window.pop()

    def __iter__(self):
        buf = []
        for s in self._samples():
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._pack(buf)
                buf = []
        if buf and not self.drop_last:
            yield self._pack(buf)

    def _pack(self, buf):
        out = {}
        for si, (name, dt, dim) in enumerate(self.slots):
            arr = np.zeros((len(buf), dim), dtype=dt)
            lens = np.zeros(len(buf), dtype=np.int64)
            for i, sample in enumerate(buf):
                v = sample[si][:dim]
                arr[i, :len(v)] = v
                lens[i] = len(sample[si])
            out[name] = arr
            out[name + ".lens"] = lens
        return out

"""ctypes bindings for the native C++ runtime (native/).

The reference crosses Python↔C++ at pybind (pybind/pybind.cc); here the
boundary is a stable C ABI (native/src/c_api.cc) loaded with ctypes — no
compiled Python extension needed, and the same .so serves the pure-C++
trainer path. Builds on demand with `make` if the .so is missing; every
consumer degrades to a pure-Python fallback when AVAILABLE is False.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libpaddle_tpu_native.so")

_lib = None
_lock = threading.Lock()


def _build():
    subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True,
                   capture_output=True)


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH):
            _build()
        lib = ctypes.CDLL(_SO_PATH)
        # signatures
        lib.ptn_pool_create.restype = ctypes.c_void_p
        lib.ptn_pool_create.argtypes = [ctypes.c_uint64]
        lib.ptn_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.ptn_pool_alloc.restype = ctypes.c_void_p
        lib.ptn_pool_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ptn_pool_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.ptn_pool_stats.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_uint64)] * 4
        lib.ptn_feed_create.restype = ctypes.c_void_p
        lib.ptn_feed_create.argtypes = [
            ctypes.c_int32, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32]
        lib.ptn_feed_destroy.argtypes = [ctypes.c_void_p]
        lib.ptn_feed_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptn_feed_set_shuffle.argtypes = [ctypes.c_void_p,
                                             ctypes.c_int32,
                                             ctypes.c_uint64]
        lib.ptn_feed_start.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ptn_feed_stop.argtypes = [ctypes.c_void_p]
        lib.ptn_feed_next.restype = ctypes.c_int64
        lib.ptn_feed_next.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_void_p),
                                      ctypes.POINTER(ctypes.c_int64)]
        lib.ptn_feed_samples_parsed.restype = ctypes.c_uint64
        lib.ptn_feed_samples_parsed.argtypes = [ctypes.c_void_p]
        lib.ptn_feed_parse_errors.restype = ctypes.c_uint64
        lib.ptn_feed_parse_errors.argtypes = [ctypes.c_void_p]
        lib.ptn_profiler_push.argtypes = [ctypes.c_char_p]
        lib.ptn_profiler_pop.argtypes = [ctypes.c_char_p]
        lib.ptn_profiler_dump.restype = ctypes.c_int
        lib.ptn_profiler_dump.argtypes = [ctypes.c_char_p]
        lib.ptn_version.restype = ctypes.c_char_p
        _lib = lib
        return lib


try:
    _load()
    AVAILABLE = True
except Exception:  # toolchain missing — consumers fall back to Python
    AVAILABLE = False


def version() -> str:
    return _load().ptn_version().decode()


class NativeDataFeed:
    """Multi-threaded MultiSlot-format file feeder (C++ parse + batch).

    Slots: list of (name, dtype, dim) with dtype in {"float32", "int64"}.
    Yields dict name -> np.ndarray [batch, dim]; `<name>.lens` holds the
    pre-pad value count per row (the LoD-metadata replacement).
    """

    def __init__(self, slots, batch_size, capacity=8, drop_last=False):
        self._lib = _load()
        self.slots = [(n, np.dtype(d), int(dim)) for n, d, dim in slots]
        self.batch_size = int(batch_size)
        names = (ctypes.c_char_p * len(slots))(
            *[n.encode() for n, _, _ in self.slots])
        types = (ctypes.c_int32 * len(slots))(
            *[0 if d == np.float32 else 1 for _, d, _ in self.slots])
        dims = (ctypes.c_int64 * len(slots))(
            *[dim for _, _, dim in self.slots])
        self._h = self._lib.ptn_feed_create(
            len(slots), names, types, dims, self.batch_size, capacity,
            1 if drop_last else 0)
        self._started = False

    def add_file(self, path):
        self._lib.ptn_feed_add_file(self._h, path.encode())

    def set_filelist(self, paths):
        for p in paths:
            self.add_file(p)

    def set_shuffle(self, on=True, seed=0):
        self._lib.ptn_feed_set_shuffle(self._h, 1 if on else 0, seed)

    def start(self, n_threads=4):
        self._lib.ptn_feed_start(self._h, n_threads)
        self._started = True

    def stop(self):
        if self._h:
            self._lib.ptn_feed_stop(self._h)
        self._started = False

    @property
    def samples_parsed(self):
        return self._lib.ptn_feed_samples_parsed(self._h)

    @property
    def parse_errors(self):
        return self._lib.ptn_feed_parse_errors(self._h)

    def __iter__(self):
        if not self._started:
            self.start()
        n = len(self.slots)
        while True:
            arrays = [np.zeros((self.batch_size, dim), dtype=d)
                      for _, d, dim in self.slots]
            bufs = (ctypes.c_void_p * n)(
                *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
            lens = np.zeros(n * self.batch_size, dtype=np.int64)
            bs = self._lib.ptn_feed_next(
                self._h, bufs,
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            if bs == 0:
                self._started = False
                return
            out = {}
            for i, (name, _, _) in enumerate(self.slots):
                out[name] = arrays[i][:bs]
                out[name + ".lens"] = \
                    lens[i * self.batch_size:i * self.batch_size + bs]
            yield out

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ptn_feed_destroy(self._h)
                self._h = None
        except Exception:
            pass


class profiler_scope:
    """RAII host-phase annotation recorded in the native profiler."""

    def __init__(self, name):
        self.name = name.encode()

    def __enter__(self):
        if AVAILABLE:
            _load().ptn_profiler_push(self.name)
        return self

    def __exit__(self, *exc):
        if AVAILABLE:
            _load().ptn_profiler_pop(self.name)
        return False


def profiler_enable():
    if AVAILABLE:
        _load().ptn_profiler_enable()


def profiler_disable():
    if AVAILABLE:
        _load().ptn_profiler_disable()


def profiler_reset():
    if AVAILABLE:
        _load().ptn_profiler_reset()


def profiler_dump(path) -> int:
    if AVAILABLE:
        return _load().ptn_profiler_dump(path.encode())
    return -1

"""paddle_tpu: a TPU-native deep-learning framework.

Capability target: PaddlePaddle Fluid 1.5 (see SURVEY.md) — same user-facing
semantics (Program IR, Executor feed/fetch, layers/optimizers, distributed
training) rebuilt idiomatically on JAX/XLA/Pallas/pjit:

- a Program lowers to ONE XLA computation per (feed-shapes, fetch) slice;
- autodiff = vjp over op lowerings, appended as IR grad ops;
- parallelism = jax.sharding Mesh + GSPMD collectives over ICI, not
  NCCL op-handles;
- the eager path (dygraph) runs the same op registry op-by-op under jax.

Top-level namespace mirrors `paddle.fluid` (reference
python/paddle/fluid/__init__.py) so reference users can port scripts by
changing the import.
"""

from . import ops  # noqa: F401  — registers all op lowerings
from . import average  # noqa: F401
from .framework import (Program, program_guard, default_main_program,  # noqa: F401
                        default_startup_program, name_scope, unique_name,
                        ParamAttr, WeightNormParamAttr, Variable,
                        in_dygraph_mode, cpu_places, load_op_library)
from .core.place import (CPUPlace, XLAPlace, TPUPlace, CUDAPlace,  # noqa: F401
                         CUDAPinnedPlace)
from .core.scope import Scope, global_scope, scope_guard  # noqa: F401
from .core.lod import LoDTensor, LoDTensorArray  # noqa: F401
from .executor import Executor  # noqa: F401
from .parallel.api import ParallelExecutor  # noqa: F401
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa: F401
from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import nets  # noqa: F401
from . import metrics  # noqa: F401
from . import io  # noqa: F401
from . import io_sharded  # noqa: F401
from .io_sharded import (save_sharded_persistables,  # noqa: F401
                         load_sharded_persistables)
from . import dygraph  # noqa: F401
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401
from . import debugger  # noqa: F401
from . import trainer_desc  # noqa: F401
from .core import memory  # noqa: F401
from . import dataset  # noqa: F401
from .dataset import DatasetFactory  # noqa: F401
from . import contrib  # noqa: F401
from . import datasets  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import resilience  # noqa: F401
from . import reader_decorator  # noqa: F401
from . import transpiler  # noqa: F401
from .transpiler import (DistributeTranspiler,  # noqa: F401
                         DistributeTranspilerConfig, memory_optimize,
                         release_memory)
from .backward import append_backward, gradients  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .reader import DataLoader, PyReader  # noqa: F401
from .clip import set_gradient_clip  # noqa: F401
from .install_check import run_check  # noqa: F401
from .core.flags import FLAGS, get_flags, set_flags  # noqa: F401


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data: batch dim NOT auto-prepended (reference data.py)."""
    return layers.data(name, shape, append_batch_size=False, dtype=dtype,
                       lod_level=lod_level)


embedding = layers.embedding
one_hot = layers.one_hot

__version__ = "0.1.0"

"""Initializers: emit init ops into the startup program.

Reference: python/paddle/fluid/initializer.py — each initializer appends a
fill_constant / uniform_random / gaussian_random op on the parameter into the
startup block; running the startup program materialises params in the Scope.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier",
           "MSRA", "Bilinear", "NumpyArrayInitializer",
           "ConstantInitializer", "UniformInitializer", "NormalInitializer",
           "TruncatedNormalInitializer", "XavierInitializer",
           "MSRAInitializer", "BilinearInitializer"]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        block.append_op("fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "value": float(self.value)},
                        infer_shape=False)


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high = low, high

    def __call__(self, var, block):
        block.append_op("uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "min": float(self.low),
                               "max": float(self.high)},
                        infer_shape=False)


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale = loc, scale

    def __call__(self, var, block):
        block.append_op("gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc),
                               "std": float(self.scale)},
                        infer_shape=False)


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale = loc, scale

    def __call__(self, var, block):
        block.append_op("truncated_gaussian_random",
                        outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc),
                               "std": float(self.scale)},
                        infer_shape=False)


def _fans(var):
    """(fan_in, fan_out). FC weights are [in, out]; conv filters are
    [out_c, in_c, kh, kw] so fan_in = in_c*kh*kw (reference
    initializer.py _compute_fans)."""
    shape = var.shape
    if len(shape) < 2:
        return shape[0] if shape else 1, shape[0] if shape else 1
    if len(shape) == 2:
        return shape[0], shape[1]
    recept = int(np.prod(shape[2:]))
    return shape[1] * recept, shape[0] * recept


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out = uniform, fan_in, fan_out

    def __call__(self, var, block):
        fin, fout = _fans(var)
        fin = self.fan_in if self.fan_in is not None else fin
        fout = self.fan_out if self.fan_out is not None else fout
        if self.uniform:
            limit = math.sqrt(6.0 / (fin + fout))
            UniformInitializer(-limit, limit)(var, block)
        else:
            std = math.sqrt(2.0 / (fin + fout))
            NormalInitializer(0.0, std)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in = uniform, fan_in

    def __call__(self, var, block):
        fin, _ = _fans(var)
        fin = self.fan_in if self.fan_in is not None else fin
        if self.uniform:
            limit = math.sqrt(6.0 / fin)
            UniformInitializer(-limit, limit)(var, block)
        else:
            NormalInitializer(0.0, math.sqrt(2.0 / fin))(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose
    (initializer.py BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear init needs a 4-D filter")
        c, k, h, w = shape
        f = math.ceil(w / 2.0)
        cc = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        for i in range(np.prod(shape)):
            x = i % w
            y = (i // w) % h
            v = (1 - abs(x / f - cc)) * (1 - abs(y / f - cc))
            weight[i // (w * h * k) % c, (i // (w * h)) % k, y, x] = v
        NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(self.value.shape),
                               "dtype": var.dtype,
                               "values": self.value},
                        infer_shape=False)


# fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


# -- CPU-init shims (reference initializer.py:30-60) ----------------------
# On TPU the startup program compiles to XLA wherever the Executor
# targets; there is no separate "init on CPU then copy" path to select,
# so the context manager is accepted and ignored (weights land on the
# device that runs startup).
import contextlib as _contextlib

_force_init_on_cpu_flag = False


def force_init_on_cpu():
    return _force_init_on_cpu_flag


@_contextlib.contextmanager
def init_on_cpu():
    global _force_init_on_cpu_flag
    prev = _force_init_on_cpu_flag
    _force_init_on_cpu_flag = True
    try:
        yield
    finally:
        _force_init_on_cpu_flag = prev

"""Sharded (orbax-style) checkpointing for dp x tp-sharded state.

Reference contrast: io.py save_persistables writes whole tensors from a
single host (operators/save_op.cc serializes the full buffer). On a
sharded jax.Array that would force an all-gather to host 0. Here each
process writes ONLY its addressable shards (one .npy per distinct shard
index) plus a JSON manifest recording global shape/dtype, the
PartitionSpec, and the byte layout of every shard; load rebuilds the
arrays shard-locally via jax.make_array_from_callback over mmap'd
files — no host ever materialises a full gathered tensor.

The manifest also carries the program's op-version map
(framework.op_version_map); Program.from_dict / load_sharded check it so
a checkpoint produced by a NEWER op implementation is refused instead
of silently misinterpreted (reference op_compatible_info.h).
"""
from __future__ import annotations

import json
import os
from typing import Optional

import jax
import numpy as np

from .core.scope import global_scope
from .framework import Program, op_version_map, check_op_versions
from .io import atomic_np_save, atomic_write_text

__all__ = ["save_sharded_persistables", "load_sharded_persistables"]

_MANIFEST = "manifest.json"


def _spec_to_json(spec):
    if spec is None:
        return None
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _spec_from_json(j):
    from jax.sharding import PartitionSpec as P
    if j is None:
        return P()
    return P(*[tuple(e) if isinstance(e, list) else e for e in j])


def _shard_file(name, k):
    return f"{name.replace('/', '%2F')}__shard{k}.npy"


def save_sharded_persistables(executor, dirname, main_program=None,
                              scope=None):
    """Write each persistable var's addressable shards + a manifest.
    Safe on a single device too (one shard per var)."""
    from .framework import default_main_program
    program = main_program or default_main_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)

    manifest = {"op_versions": op_version_map(program), "vars": {}}
    proc = jax.process_index()

    for v in program.list_vars():
        if not (v.persistable and not v.is_data):
            continue
        if not scope.has(v.name):
            continue
        arr = scope.get(v.name)
        entry = {"dtype": None, "shape": None, "spec": None, "shards": []}
        if isinstance(arr, jax.Array) and hasattr(arr, "sharding"):
            entry["shape"] = list(arr.shape)
            entry["dtype"] = str(arr.dtype)
            spec = getattr(arr.sharding, "spec", None)
            entry["spec"] = _spec_to_json(spec)
            seen = set()
            for k, shard in enumerate(arr.addressable_shards):
                index = tuple(
                    (0 if s.start is None else int(s.start),
                     int(arr.shape[d]) if s.stop is None else int(s.stop))
                    for d, s in enumerate(shard.index))
                if index in seen:
                    continue  # replica of an already-saved shard
                seen.add(index)
                fn = _shard_file(v.name, f"{proc}_{k}")
                atomic_np_save(os.path.join(dirname, fn),
                               np.asarray(shard.data))
                entry["shards"].append({"file": fn,
                                        "index": [list(i) for i in index]})
        else:
            a = np.asarray(scope.get_numpy(v.name))
            entry["shape"] = list(a.shape)
            entry["dtype"] = str(a.dtype)
            fn = _shard_file(v.name, f"{proc}_0")
            atomic_np_save(os.path.join(dirname, fn), a)
            entry["shards"].append(
                {"file": fn,
                 "index": [[0, int(s)] for s in a.shape]})
        manifest["vars"][v.name] = entry

    # process 0 owns the manifest (single-host: always process 0);
    # multi-host runs merge shard lists per process file then combine.
    # The manifest commits the checkpoint, so it goes LAST and
    # atomically: a crash anywhere above leaves the previous manifest
    # (and the previous complete checkpoint it describes) intact —
    # freshly-renamed orphan shards are harmless until a manifest
    # references them.
    mpath = os.path.join(dirname, _MANIFEST if proc == 0
                         else f"manifest.{proc}.json")
    atomic_write_text(mpath,
                      json.dumps(manifest, indent=1, sort_keys=True))
    return manifest


def load_sharded_persistables(executor, dirname, main_program=None,
                              mesh=None, scope=None):
    """Rebuild each var with its saved sharding on `mesh` (or the saved
    replicated layout when mesh is None). Shard-local: every device
    reads only the file regions covering its own shard."""
    from jax.sharding import NamedSharding
    from .framework import default_main_program
    program = main_program or default_main_program()
    scope = scope or global_scope()

    with open(os.path.join(dirname, _MANIFEST)) as f:
        manifest = json.load(f)
    # multi-host save: merge every process's shard lists into one view
    import glob
    for extra in sorted(glob.glob(os.path.join(dirname,
                                               "manifest.*.json"))):
        with open(extra) as f:
            m2 = json.load(f)
        for name, entry in m2.get("vars", {}).items():
            base = manifest["vars"].setdefault(name, entry)
            if base is not entry:
                known = {tuple(tuple(i) for i in s["index"])
                         for s in base["shards"]}
                for s in entry["shards"]:
                    if tuple(tuple(i) for i in s["index"]) not in known:
                        base["shards"].append(s)
    check_op_versions(manifest.get("op_versions", {}))

    for name, entry in manifest["vars"].items():
        if main_program is not None and \
                not program.global_block().has_var(name):
            continue
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        shards = entry["shards"]

        def _assemble():
            # assemble the full array from all shards, verifying they
            # cover it (a partial multi-host checkpoint must fail
            # loudly, not return uninitialized memory)
            full = np.empty(shape, dtype)
            covered = 0
            for s in shards:
                sl = tuple(slice(a, b) for a, b in s["index"])
                full[sl] = np.load(os.path.join(dirname, s["file"]))
                covered += int(np.prod([b - a for a, b in s["index"]]))
            if covered < int(np.prod(shape)):
                raise ValueError(
                    f"checkpoint for {name!r} covers only {covered} of "
                    f"{int(np.prod(shape))} elements — missing process "
                    f"shards? (manifest.*.json files must accompany "
                    f"multi-host checkpoints)")
            return full

        if mesh is None:
            scope.set(name, _assemble())  # host serving
            continue
        if entry["spec"] is None:
            # saved without a NamedSharding spec (e.g. positional/GSPMD
            # sharding): assemble everything, place replicated
            sharding = NamedSharding(mesh, _spec_from_json(None))
            scope.set(name, jax.device_put(_assemble(), sharding))
            continue
        if len(shards) == 1 and all(
                i == [0, s] for i, s in zip(shards[0]["index"], shape)):
            # replicated / single full shard: plain load + placement
            full = np.load(os.path.join(dirname, shards[0]["file"]))
            sharding = NamedSharding(mesh, _spec_from_json(entry["spec"]))
            scope.set(name, jax.device_put(full, sharding))
            continue

        sharding = NamedSharding(mesh, _spec_from_json(entry["spec"]))
        mmaps = {s["file"]: np.load(os.path.join(dirname, s["file"]),
                                    mmap_mode="r") for s in shards}
        index_of = {tuple(tuple(i) for i in s["index"]): s["file"]
                    for s in shards}

        def make(idx, index_of=index_of, mmaps=mmaps, shape=shape,
                 dtype=dtype):
            want = tuple(
                (0 if s.start is None else int(s.start),
                 int(shape[d]) if s.stop is None else int(s.stop))
                for d, s in enumerate(idx))
            f = index_of.get(want)
            if f is not None:   # exact shard match: read it whole
                return np.ascontiguousarray(mmaps[f])
            # otherwise find a saved shard covering the wanted region
            for saved, fn in index_of.items():
                if all(ws >= ss and we <= se for (ws, we), (ss, se)
                       in zip(want, saved)):
                    rel = tuple(slice(ws - ss, we - ss)
                                for (ws, we), (ss, se)
                                in zip(want, saved))
                    return np.ascontiguousarray(mmaps[fn][rel])
            raise ValueError(
                f"no saved shard covers index {want} of {shape}; "
                f"checkpoint mesh is incompatible with the load mesh")

        arr = jax.make_array_from_callback(shape, sharding, make)
        scope.set(name, arr)
    return manifest

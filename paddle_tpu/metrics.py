"""Host-side streaming metrics (reference: python/paddle/fluid/metrics.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall",
           "Accuracy", "ChunkEvaluator", "EditDistance", "Auc",
           "DetectionMAP"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if isinstance(v, (int, float)) and not k.startswith("_"):
                setattr(self, k, 0 if isinstance(v, int) else 0.0)

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data in Accuracy")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels != 1)))

    def eval(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds != 1) & (labels == 1)))

    def eval(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._n = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def reset(self):
        self._stat_pos[:] = 0
        self._stat_neg[:] = 0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, -1] if preds.ndim > 1 else preds
        bucket = np.clip((pos_prob * self._n).astype(int), 0, self._n)
        for b, l in zip(bucket, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos * tot_neg == 0:
            return 0.0
        tp_prev = np.concatenate([[0], tp[:-1]])
        fp_prev = np.concatenate([[0], fp[:-1]])
        area = np.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
        return float(area / (tot_pos * tot_neg))


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        p = self.num_correct_chunks / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        r = self.num_correct_chunks / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        return p, r, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data in EditDistance")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class DetectionMAP(MetricBase):
    """Streaming detection mAP (reference metrics.py:805).

    The reference accumulates TruePos/FalsePos/PosCount as graph state
    threaded through detection_map's accum inputs — shapes there are
    data-dependent LoD, which XLA's static-shape model rejects. The
    TPU-first redesign keeps per-batch matching in the detection_map op
    (ops/parity_final.py) and moves the ACCUMULATION to the host: call
    `update(detections, gt_label, gt_box, gt_difficult)` once per image
    with numpy arrays (the fetched op inputs), then `eval()` returns
    the mAP over everything seen. The matching + AP math mirrors
    detection_map_op.h:308-475 (strict overlap > threshold, prediction
    ClipBBox, one GT consumed per match, integral/11point AP).

    detections: [M, 6] (label, confidence, xmin, ymin, xmax, ymax)
    gt_label: [N, 1]; gt_box: [N, 4]; gt_difficult: [N, 1] or None.
    """

    def __init__(self, class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral", name=None):
        super().__init__(name)
        if ap_version not in ("integral", "11point"):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self._class_num = class_num
        self._background = background_label
        self._thr = overlap_threshold
        self._eval_difficult = evaluate_difficult
        self._ap_version = ap_version
        self.reset()

    def reset(self):
        # per class: npos count and (score, is_tp) match records
        self._npos = {}
        self._records = {}

    def update(self, detections, gt_label, gt_box, gt_difficult=None):
        """One image's detections + ground truth (numpy). Matching math
        is shared with the detection_map op (core/detection_eval.py)."""
        from .core.detection_eval import match_class

        det = np.asarray(detections, np.float32).reshape(-1, 6)
        gl = np.asarray(gt_label).reshape(-1).astype(np.int64)
        gb = np.asarray(gt_box, np.float32).reshape(-1, 4)
        gd = np.zeros(len(gl), bool) if gt_difficult is None else \
            np.asarray(gt_difficult).reshape(-1) != 0
        for cls in set(gl.tolist()) | set(det[:, 0].astype(int).tolist()):
            if cls == self._background:
                continue
            sel = gl == cls
            gts, diff = gb[sel], gd[sel]
            npos = int(len(gts) if self._eval_difficult
                       else (~diff).sum())
            self._npos[cls] = self._npos.get(cls, 0) + npos
            d = det[det[:, 0] == cls]
            if len(d) == 0:
                continue
            self._records.setdefault(cls, []).extend(
                match_class(d[:, 1:6], gts, diff, self._thr,
                            self._eval_difficult))

    def eval(self):
        from .core.detection_eval import average_precision

        aps = [ap for cls, npos in self._npos.items()
               if (ap := average_precision(self._records.get(cls, []),
                                           npos,
                                           self._ap_version)) is not None]
        return float(np.mean(aps)) if aps else 0.0

"""Program IR + graph-construction frontend.

Reference analogue: python/paddle/fluid/framework.py (Variable:561,
Operator:1660, Block:2112, Program:3495) over the C++ ProgramDesc protos
(paddle/fluid/framework/framework.proto). Differences by design:

- One representation. The reference keeps a Python wrapper per C++ Desc per
  proto message; here the Python objects ARE the IR, serializable to a plain
  dict (JSON) for checkpoints / inference export.
- Shape inference is derived, not hand-written: appending an op runs
  `jax.eval_shape` over the op's registered lowering (see core/lowering.py),
  so there is no per-op InferShape to keep in sync with the kernel.
- The whole block lowers to ONE XLA computation at execution time
  (core/lowering.py), instead of per-op kernel dispatch (executor.cc:451).
"""
from __future__ import annotations

import contextlib
import copy
import hashlib
import json
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from .core.dtypes import convert_dtype

__all__ = [
    "Variable", "Parameter", "Operator", "Block", "Program",
    "default_main_program", "default_startup_program", "program_guard",
    "name_scope", "unique_name", "ParamAttr", "grad_var_name", "cpu_places",
    "in_dygraph_mode",
]

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


class UniqueNameGenerator:
    def __init__(self):
        self.ids = defaultdict(int)
        self.prefix = ""

    def __call__(self, key: str) -> str:
        name = f"{self.prefix}{key}_{self.ids[key]}"
        self.ids[key] += 1
        return name


_name_gen = UniqueNameGenerator()


class _UniqueNameModule:
    """Mimics fluid.unique_name: unique_name.generate(key)."""

    @staticmethod
    def generate(key):
        return _name_gen(key)

    @staticmethod
    @contextlib.contextmanager
    def guard(prefix=""):
        global _name_gen
        old = _name_gen
        _name_gen = UniqueNameGenerator()
        _name_gen.prefix = prefix
        try:
            yield
        finally:
            _name_gen = old


unique_name = _UniqueNameModule()

_name_scope_stack: List[str] = []


@contextlib.contextmanager
def name_scope(prefix):
    _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        _name_scope_stack.pop()


class Variable:
    """A named tensor in a Block (reference: framework.py:561).

    Holds static metadata only; values live in a Scope at run time.
    """

    def __init__(self, block, name, shape=None, dtype="float32", lod_level=0,
                 persistable=False, stop_gradient=False, is_data=False,
                 trainable=True, **kw):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.trainable = trainable

    @property
    def is_parameter(self):
        return isinstance(self, Parameter)

    # -- operator sugar so user code reads like fluid --------------------
    def _binary(self, other, op):
        from .layers import math_ops
        return math_ops.elementwise_binary(op, self, other)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __repr__(self):
        p = " persistable" if self.persistable else ""
        return f"Var({self.name}: {self.dtype}{list(self.shape or [])}{p})"

    def to_dict(self):
        return {
            "name": self.name, "shape": list(self.shape or []),
            "dtype": self.dtype, "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient, "is_data": self.is_data,
            "trainable": self.trainable,
            "is_parameter": self.is_parameter,
        }


class Parameter(Variable):
    """Trainable persistable variable (reference: framework.py:4439)."""

    def __init__(self, block, name, shape, dtype, trainable=True,
                 regularizer=None, optimize_attr=None, **kw):
        super().__init__(block, name, shape=shape, dtype=dtype,
                         persistable=True, stop_gradient=not trainable,
                         trainable=trainable, **kw)
        self.regularizer = regularizer
        self.optimize_attr = optimize_attr or {"learning_rate": 1.0}
        self.do_model_average = kw.get("do_model_average", False)


class Operator:
    """One op in a block (reference: framework.py:1660 / OpDesc).

    inputs/outputs: {slot: [var names]}. attrs: JSON-able values only
    (sub-block references are stored as {"__block__": idx}).
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None,
                 op_id=None):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {
            k: list(v) for k, v in (inputs or {}).items()}
        self.outputs: Dict[str, List[str]] = {
            k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        # Stable per-program id: PRNG key folding for stateful ops (dropout)
        # so forward and vjp-grad see identical randomness.
        self.id = op_id if op_id is not None else block.program._next_op_id()

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def has_attr(self, name):
        return name in self.attrs

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def __repr__(self):
        return (f"Op({self.type}: " +
                ", ".join(f"{k}={v}" for k, v in self.inputs.items()) +
                " -> " + ", ".join(f"{k}={v}" for k, v in self.outputs.items())
                + ")")

    def to_dict(self):
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs, "attrs": _jsonable_attrs(self.attrs),
                "id": self.id}


def _jsonable_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


def _attrs_from_json(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = np.asarray(v["__ndarray__"], dtype=v["dtype"])
        else:
            out[k] = v
    return out


class Block:
    """A straight-line list of ops + a symbol table (framework.py:2112)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    # -- vars ------------------------------------------------------------
    def create_var(self, name=None, **kw):
        name = name or unique_name.generate("tmp")
        var = Variable(self, name, **kw)
        self.vars[name] = var
        self.program._fp_cache = None
        return var

    def create_parameter(self, name, shape, dtype, **kw):
        p = Parameter(self, name, shape, dtype, **kw)
        self.vars[name] = p
        self.program._fp_cache = None
        return p

    def var(self, name) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError(f"var {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = (self.program.blocks[blk.parent_idx]
                   if blk.parent_idx >= 0 else None)
        return None

    @property
    def parent(self):
        return (self.program.blocks[self.parent_idx]
                if self.parent_idx >= 0 else None)

    # -- ops -------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None,
                  infer_shape=True):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._fp_cache = None
        if infer_shape:
            from .core import lowering
            try:
                lowering.infer_op_shapes(op, self)
            except NotImplementedError:
                pass  # op without lowering yet; shapes must be pre-set
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._fp_cache = None
        return op

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def to_dict(self):
        return {"idx": self.idx, "parent_idx": self.parent_idx,
                "vars": [v.to_dict() for v in self.vars.values()],
                "ops": [o.to_dict() for o in self.ops]}


class Program:
    """Serializable multi-block program (framework.py:3495)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.random_seed = 0
        self._current_block_idx = 0
        self._op_counter = 0
        self._version = 1
        self._fp_cache: Optional[str] = None
        # LoD bookkeeping: var name -> name of its companion sequence-
        # lengths var. The TPU representation of a ragged (LoD) tensor is
        # (padded [B, T, ...], lengths [B]) — reference lod_tensor.h:104
        # carries offsets on the tensor itself; here the link is program
        # metadata so it survives serialization and build-time layer
        # propagation (layer_helper.py) keeps it attached to downstream
        # activations.
        self.lod_link: Dict[str, str] = {}

    def _next_op_id(self):
        self._op_counter += 1
        return self._op_counter

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def _create_block(self, parent_idx=None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent_idx=parent)
        self.blocks.append(blk)
        self._current_block_idx = blk.idx
        return blk

    def _rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def all_parameters(self):
        return [v for blk in self.blocks for v in blk.all_parameters()]

    def clone(self, for_test=False) -> "Program":
        p = copy.deepcopy(self)
        p._fp_cache = None
        if for_test:
            for blk in p.blocks:
                # drop backward + optimizer ops (reference framework.py
                # clone(for_test=True) semantics). Filter, don't
                # truncate: forward ops appended AFTER minimize()
                # (metrics, evaluators) must survive. Backward ops
                # produce @GRAD vars; optimizer ops consume them; LR
                # schedulers and accumulator ticks (increment on
                # @STEP_COUNTER@, beta-pow scaling) mutate ONLY
                # persistable state in place — running them during eval
                # would corrupt the training schedule.
                def _mutates_state_only(op, blk):
                    outs = [n for ns in op.outputs.values()
                            for n in ns if n]
                    if not outs:
                        return False
                    ins = {n for ns in op.inputs.values() for n in ns}
                    for n in outs:
                        v = blk._find_var_recursive(n)
                        if v is None or not v.persistable or n not in ins:
                            return False
                    return True

                def _is_train_op(op, blk=blk):
                    if op.type.startswith("grad::"):
                        return True
                    names = [n for ns in list(op.outputs.values()) +
                             list(op.inputs.values()) for n in ns if n]
                    if any(n.endswith("@GRAD") for n in names):
                        return True
                    return _mutates_state_only(op, blk)
                blk.ops = [op for op in blk.ops if not _is_train_op(op)]
                for op in blk.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
                    if op.type == "dropout":
                        op.attrs["is_test"] = True
                    if op.type in ("batch_norm", "sync_batch_norm"):
                        op.attrs["is_test"] = True
        return p

    # -- serialization ---------------------------------------------------
    def to_dict(self):
        d = {"version": self._version, "random_seed": self.random_seed,
             "op_versions": op_version_map(self),
             "blocks": [b.to_dict() for b in self.blocks]}
        if self.lod_link:
            d["lod_link"] = dict(self.lod_link)
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d) -> "Program":
        check_op_versions(d.get("op_versions", {}))
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                vd = dict(vd)
                is_param = vd.pop("is_parameter", False)
                name = vd.pop("name")
                if is_param:
                    vd.pop("persistable", None)
                    vd.pop("stop_gradient", None)
                    blk.create_parameter(
                        name, vd.pop("shape"), vd.pop("dtype"),
                        trainable=vd.pop("trainable", True), **vd)
                else:
                    vd.pop("trainable", None)
                    blk.create_var(name=name, **vd)
            for od in bd["ops"]:
                blk.ops.append(Operator(
                    blk, od["type"], od["inputs"], od["outputs"],
                    _attrs_from_json(od["attrs"]), op_id=od.get("id")))
            p.blocks.append(blk)
        p._op_counter = max(
            (op.id for b in p.blocks for op in b.ops), default=0)
        p.lod_link = dict(d.get("lod_link", {}))
        return p

    @staticmethod
    def from_json(s) -> "Program":
        return Program.from_dict(json.loads(s))

    def fingerprint(self) -> str:
        """Stable hash for the executable cache key. Cached; any
        append_op/create_var invalidates (direct attr mutation on an
        existing op does not — clone first for such rewrites)."""
        if self._fp_cache is None:
            self._fp_cache = hashlib.sha1(self.to_json().encode()).hexdigest()
        return self._fp_cache

    def __repr__(self):
        n_ops = sum(len(b.ops) for b in self.blocks)
        return f"Program({len(self.blocks)} blocks, {n_ops} ops)"


# -- global default programs (framework.py:4573) -------------------------
_main_program = Program()
_startup_program = Program()


def op_version_map(program) -> dict:
    """{op type -> registered semantic version} for every op the program
    uses (reference op_compatible_info: version map saved with the
    program and checked on load)."""
    from .core.registry import REGISTRY
    out = {}
    for blk in program.blocks:
        for op in blk.ops:
            if op.type not in out:
                out[op.type] = REGISTRY.get(op.type).version \
                    if REGISTRY.has(op.type) else 1
    return out


def check_op_versions(saved: dict):
    """Refuse to load a program/checkpoint whose ops are NEWER than this
    build supports (reference op_compatible_info.h DEFINITELY_NOT)."""
    from .core.registry import REGISTRY
    problems = []
    for t, v in (saved or {}).items():
        if not REGISTRY.has(t):
            problems.append(f"{t!r} (not registered in this build)")
        elif int(v) > REGISTRY.get(t).version:
            problems.append(
                f"{t!r} (saved v{v} > supported "
                f"v{REGISTRY.get(t).version})")
    if problems:
        raise RuntimeError(
            "incompatible saved program: " + "; ".join(problems))


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    old_main, old_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = old_main, old_startup


def in_dygraph_mode():
    from . import dygraph
    return dygraph.enabled()


def cpu_places(n=1):
    from .core.place import CPUPlace
    return [CPUPlace() for _ in range(n)]


class ParamAttr:
    """Parameter attribute bundle (reference: param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 gradient_clip=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.gradient_clip = gradient_clip

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if arg is False:
            return False
        if arg is True:
            return ParamAttr()
        from .initializer import Initializer
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        raise TypeError(f"bad ParamAttr spec {arg!r}")


class WeightNormParamAttr(ParamAttr):
    """Weight-normalized parameter attribute (reference: param_attr.py
    WeightNormParamAttr — reparameterizes w = g * v / ||v||). The `dim`
    is recorded; LayerHelper treats it as a plain ParamAttr (the
    normalization itself is an optimizer/graph rewrite concern)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, gradient_clip=None):
        super().__init__(name, initializer, learning_rate, regularizer,
                         trainable, do_model_average, gradient_clip)
        self.dim = dim


def load_op_library(lib_path):
    """Load user-defined ops into the registry.

    Reference: framework.py:4752 fluid.load_op_library dlopens a C++ op
    .so and merges its registrations into OpInfoMap
    (framework/load_op_lib.h:42). The TPU-native custom-op contract is a
    PYTHON module that calls paddle_tpu.core.registry.register_op with a
    jax/pallas lowering (the analogue of tests/custom_op/relu_op.cc) —
    pass its .py path. Returns the list of newly registered op types.
    """
    from .core.registry import REGISTRY

    if not lib_path.endswith(".py"):
        raise ValueError(
            "load_op_library takes a .py module registering jax/pallas "
            "lowerings via paddle_tpu.core.registry.register_op; native "
            "code belongs inside the kernel (pallas) or the runtime "
            "(native/), not in per-op .so plugins")
    import importlib.util

    before = set(REGISTRY.types())
    spec = importlib.util.spec_from_file_location(
        f"paddle_tpu_custom_{abs(hash(lib_path))}", lib_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return sorted(set(REGISTRY.types()) - before)

"""Collective fleet: multi-process data-parallel training.

Reference: python/paddle/fluid/incubate/fleet/collective/__init__.py —
`Collective` fleet (:41), `DistributedStrategy(fluid.BuildStrategy)`
(:94-108, adds local_sgd/recompute/nccl_comm_num/hierarchical_allreduce
knobs) and `CollectiveOptimizer` (:142) whose minimize applies the
collective transpiler. On TPU the transpiled c_allreduce ops ride XLA
collectives over ICI; cross-host bootstrap is jax.distributed.initialize
(the c_gen_nccl_id analogue) driven by the role maker's env contract.
"""
from __future__ import annotations

from ....compiler import BuildStrategy
from ....transpiler.collective import GradAllReduce, LocalSGD
from ..base.fleet_base import DistributedOptimizer, Fleet

__all__ = ["fleet", "Collective", "CollectiveOptimizer",
           "DistributedStrategy"]


class DistributedStrategy(BuildStrategy):
    def __init__(self):
        super().__init__()
        self.use_local_sgd = False
        self.local_sgd_steps = 1
        self.use_dgc = False
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 2 ** 15
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 8
        self.exec_strategy = None


class Collective(Fleet):
    def __init__(self):
        super().__init__()
        self.main_program = None
        self.startup_program = None

    def init_worker(self):
        # multi-host: one jax process per host joins the platform topology
        # (the c_gen_nccl_id + c_comm_init analogue, SURVEY.md §2.8)
        import jax

        eps = self.worker_endpoints()
        if len([e for e in eps if e]) > 1:
            try:
                jax.distributed.initialize(
                    coordinator_address=eps[0],
                    num_processes=len(eps),
                    process_id=self.worker_index())
            except (RuntimeError, ValueError):
                pass  # already initialized (or single-process test run)

    def init_server(self, model_dir=None):
        raise NotImplementedError("collective mode has no servers")

    def run_server(self):
        raise NotImplementedError("collective mode has no servers")

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy, self)
        return self._optimizer


class CollectiveOptimizer(DistributedOptimizer):
    def __init__(self, optimizer, strategy=None, fleet_ref=None):
        super().__init__(optimizer, strategy or DistributedStrategy())
        self._fleet = fleet_ref

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ....framework import (default_main_program,
                                   default_startup_program)

        opt = self._optimizer
        s = self._strategy
        if getattr(s, "forward_recompute", False):
            from ....optimizer import RecomputeOptimizer
            opt = RecomputeOptimizer(opt)
            opt._set_checkpoints(list(s.recompute_checkpoints))
        if getattr(s, "use_amp", False):
            from ....contrib.mixed_precision import decorate
            opt = decorate(opt, init_loss_scaling=s.amp_loss_scaling)

        ret = opt.minimize(loss, startup_program, parameter_list,
                           no_grad_set)

        f = self._fleet
        main = loss.block.program
        startup = startup_program or default_startup_program()
        rank = f.worker_index() if f else 0
        eps = (f.worker_endpoints() if f else [""]) or [""]
        cur = eps[rank] if rank < len(eps) else ""
        nrings = getattr(s, "nccl_comm_num", 1) or 1
        if getattr(s, "use_local_sgd", False):
            t = LocalSGD(nrings=nrings,
                         k_steps=getattr(s, "local_sgd_steps", 1))
        else:
            t = GradAllReduce(nrings=nrings)
        t.transpile(startup, main, rank, eps, cur)
        if f is not None:
            f.main_program, f.startup_program = main, startup
        return ret


fleet = Collective()

"""Parameter-server fleet (transpiler-backed).

Reference: python/paddle/fluid/incubate/fleet/parameter_server/
distribute_transpiler/__init__.py — fleet wraps DistributeTranspiler:
`distributed_optimizer(...).minimize(loss)` transpiles; workers run the
rewritten trainer program, servers run listen_and_serv
(ps_server.PServerRuntime here).
"""
from __future__ import annotations

from ....transpiler import DistributeTranspiler, DistributeTranspilerConfig
from ..base.fleet_base import DistributedOptimizer, Fleet

__all__ = ["fleet", "ParameterServerFleet", "TranspilerOptimizer"]


class ParameterServerFleet(Fleet):
    def __init__(self):
        super().__init__()
        self._transpiler: DistributeTranspiler = None
        self.main_program = None
        self.startup_program = None
        self._server_runtime = None

    # -- worker side ----------------------------------------------------
    def init_worker(self):
        pass  # connections open lazily on first send

    def stop_worker(self):
        from ....distributed.rpc import RPCClient

        c = RPCClient.instance(self.worker_index())
        for ep in self.server_endpoints():
            c.send_complete(ep)
        c.close()

    # -- server side ----------------------------------------------------
    def init_server(self, model_dir=None):
        from ....core.scope import global_scope
        from ....executor import Executor

        ep = self._role_maker.current_endpoint()
        self.pserver_program = self._transpiler.get_pserver_program(ep)
        pserver_startup = self._transpiler.get_startup_program(
            ep, self.pserver_program)
        Executor().run(pserver_startup, scope=global_scope())
        if model_dir:
            from .... import io as fio
            fio.load_persistables(Executor(), model_dir,
                                  main_program=self.pserver_program)

    def run_server(self):
        from ....core.scope import global_scope
        from ....distributed.ps_server import run_pserver

        self._server_runtime = run_pserver(
            self.pserver_program, scope=global_scope(), block=True)

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = TranspilerOptimizer(optimizer, strategy, self)
        return self._optimizer


class TranspilerOptimizer(DistributedOptimizer):
    def __init__(self, optimizer, strategy=None, fleet_ref=None):
        super().__init__(optimizer, strategy or
                         DistributeTranspilerConfig())
        self._fleet = fleet_ref

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ....framework import default_startup_program

        ret = self._optimizer.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)
        f = self._fleet
        t = DistributeTranspiler(self._strategy)
        t.transpile(
            trainer_id=f.worker_index(),
            program=loss.block.program,
            pservers=",".join(f.server_endpoints()),
            trainers=f.worker_num(),
            startup_program=startup_program or default_startup_program())
        f._transpiler = t
        if f.is_worker():
            f.main_program = t.get_trainer_program()
            f.startup_program = (startup_program or
                                 default_startup_program())
        return ret


fleet = ParameterServerFleet()

"""Cluster role discovery.

Reference: python/paddle/fluid/incubate/fleet/base/role_maker.py —
RoleMakerBase subclasses discover whether this process is a WORKER or
SERVER and the cluster endpoints, either from user args
(UserDefinedRoleMaker) or from env vars set by the launcher
(PaddleCloudRoleMaker; env names match the reference's launch.py).
"""
from __future__ import annotations

import os

__all__ = ["Role", "RoleMakerBase", "UserDefinedRoleMaker",
           "PaddleCloudRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []
        self._generated = False

    def generate_role(self):
        self._generated = True

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return len(self._worker_endpoints) or 1

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._server_endpoints = list(server_endpoints or [])
        self._worker_endpoints = list(
            worker_endpoints or [""] * worker_num)


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var discovery (the reference's launcher contract):
    TRAINING_ROLE=TRAINER|PSERVER, PADDLE_TRAINER_ID,
    PADDLE_TRAINERS_NUM, PADDLE_PSERVERS_IP_PORT_LIST,
    PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINER_ENDPOINTS."""

    def __init__(self, is_collective=False):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        self._server_endpoints = [
            e for e in os.environ.get(
                "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
        self._worker_endpoints = [
            e for e in os.environ.get(
                "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]
        if self._role == Role.SERVER:
            cur = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
            self._current_id = (self._server_endpoints.index(cur)
                                if cur in self._server_endpoints else 0)
            self._current_endpoint = cur
        else:
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            self._current_endpoint = os.environ.get(
                "PADDLE_CURRENT_ENDPOINT", "")
        n = os.environ.get("PADDLE_TRAINERS_NUM")
        if n and not self._worker_endpoints:
            self._worker_endpoints = [""] * int(n)
        self._generated = True

    def current_endpoint(self):
        if not self._generated:
            self.generate_role()
        return self._current_endpoint

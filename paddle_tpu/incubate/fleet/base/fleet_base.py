"""Fleet: unified distributed-training front end.

Reference: python/paddle/fluid/incubate/fleet/base/fleet_base.py:38 —
`Fleet.init(role_maker)`, worker/server predicates, `init_worker`/
`init_server`/`run_server`/`stop_worker`, and `distributed_optimizer`
returning a DistributedOptimizer that transpiles during minimize.
"""
from __future__ import annotations

import abc

from .role_maker import PaddleCloudRoleMaker, RoleMakerBase

__all__ = ["Fleet", "DistributedOptimizer"]


class Fleet(metaclass=abc.ABCMeta):
    def __init__(self):
        self._role_maker: RoleMakerBase = None
        self._optimizer = None
        self._is_initialized = False

    # -- predicates / topology (fleet_base.py:60-180) -------------------
    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def server_num(self):
        return self._role_maker.server_num()

    def server_index(self):
        return self._role_maker.server_index()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    # -- lifecycle ------------------------------------------------------
    def init(self, role_maker=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        self._is_initialized = True
        return self

    @abc.abstractmethod
    def init_worker(self):
        ...

    @abc.abstractmethod
    def init_server(self, model_dir=None):
        ...

    @abc.abstractmethod
    def run_server(self):
        ...

    @abc.abstractmethod
    def stop_worker(self):
        ...

    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        ...


class DistributedOptimizer(metaclass=abc.ABCMeta):
    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    @abc.abstractmethod
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ...

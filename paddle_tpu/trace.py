"""Per-request distributed tracing: spans from HTTP to executor.

The monitor stack (monitor.py) answers "how is the fleet doing" —
counters, histograms, the flight recorder. This module answers "where
did THIS request spend its time": Dapper-style spans with W3C
traceparent propagation, carried across the serving stack's thread
hand-offs (DynamicBatcher submit -> worker flush, GenerationEngine
submit -> iteration loop) and dumped as JSONL or chrome://tracing JSON
that merges with the monitor's host-phase events.

Sampling is head + tail. The head decision (FLAGS_trace_sample) is made
once when a root span is created; spans are buffered per-trace either
way, and the tail rules get the final word at finish_trace(): errored
requests and requests slower than the rolling latency threshold
(FLAGS_trace_tail_slow_ms, or a rolling p95 when 0) are ALWAYS kept.
Kept traces land in a bounded in-process ring
(FLAGS_trace_ring_capacity); everything else is dropped and only
counted. This is the standard tail-based design: you cannot know a
request was slow until it finished, so you buffer cheaply and decide at
the end.

Propagation: contextvars carry the current span within a thread;
threads are crossed by stashing the Span object on the queue entry
(`_Request.span`, `_Queued.span`) and re-entering it with use_span()
on the worker side — contextvars do NOT follow objects across threads,
so every hand-off site does this explicitly.

Near-zero cost when disabled: every entry point checks
FLAGS_enable_trace through a cached flag handle (same discipline as
monitor.enabled()) and returns None; all APIs tolerate None spans, so
instrumented hot paths cost ~a function call when tracing is off.
"""
from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import monitor
from .monitor import STAT_ADD, STAT_SET

__all__ = ["Span", "enabled", "start_span", "end_span", "record_span",
           "finish_trace", "is_root", "complete_request",
           "use_span", "span", "current_span",
           "current_trace_id", "parse_traceparent", "format_traceparent",
           "new_trace_id", "new_span_id", "ring_spans", "drain_spans",
           "export_jsonl", "export_chrome_tracing", "slow_threshold_ms",
           "reset"]

_flag = None


def enabled() -> bool:
    """FLAGS_enable_trace through a cached flag handle (one None-check +
    one attribute read on the disabled fast path)."""
    global _flag
    f = _flag
    if f is None:
        from .core.flags import flag_handle
        f = _flag = flag_handle("enable_trace")
    return f.value


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


# ---------------------------------------------------------------------------
# Span
# ---------------------------------------------------------------------------

class Span:
    """One timed operation in a trace. Times are wall-clock seconds at
    start plus a perf_counter duration (monotonic — a span is immune to
    clock steps mid-request). Mutated by one thread at a time by
    construction (the hand-off sites pass ownership with the object)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t_start",
                 "dur_ms", "attrs", "events", "links", "status", "tid",
                 "_perf0", "_done")

    def __init__(self, trace_id, span_id, parent_id, name,
                 t_start=None, perf0=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t_start = time.time() if t_start is None else t_start
        self._perf0 = time.perf_counter() if perf0 is None else perf0
        self.dur_ms = None
        self.attrs: Dict[str, object] = {}
        self.events: List[dict] = []
        self.links: List[dict] = []
        self.status = "ok"
        self.tid = threading.get_ident()
        self._done = False

    def set_attr(self, key, value):
        self.attrs[key] = value
        return self

    def add_event(self, name, **attrs):
        ev = {"name": name, "ts": time.time()}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)
        return self

    def add_link(self, other: "Span"):
        """Cross-trace association (a batch span links every member
        request span without claiming parenthood over them)."""
        if other is not None:
            self.links.append({"trace_id": other.trace_id,
                               "span_id": other.span_id})
        return self

    def to_dict(self) -> dict:
        return {"kind": "span", "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "name": self.name, "t_start": self.t_start,
                "dur_ms": self.dur_ms, "status": self.status,
                "attrs": dict(self.attrs), "events": list(self.events),
                "links": list(self.links), "tid": self.tid}


class _Trace:
    """Per-trace buffer: every span of an in-flight trace, plus the head
    sampling decision, held until finish_trace() rules keep/drop."""

    __slots__ = ("trace_id", "root", "spans", "head_sampled")

    def __init__(self, trace_id, root, head_sampled):
        self.trace_id = trace_id
        self.root = root
        self.spans = [root]
        self.head_sampled = head_sampled


_LOCK = threading.Lock()
_ACTIVE: Dict[str, _Trace] = {}
_RING: "deque" = deque()
# Rolling e2e window for the tail "slower than usual" rule.
_LAT_WINDOW: "deque" = deque(maxlen=256)
_LAT_MIN_SAMPLES = 20

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("paddle_tpu_trace_span", default=None)


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    """trace_id of the current span, or None — safe to call with tracing
    disabled (histogram-exemplar call sites use this unconditionally)."""
    s = _CURRENT.get()
    return s.trace_id if s is not None else None


# ---------------------------------------------------------------------------
# Creation / completion
# ---------------------------------------------------------------------------

def start_span(name: str, parent: Optional[Span] = None,
               attrs: Optional[dict] = None,
               remote: Optional[Tuple[str, str]] = None,
               t_start: Optional[float] = None) -> Optional[Span]:
    """Start a span. With no explicit parent, the contextvar current
    span is the parent; with neither, this starts a ROOT span (new
    trace) — the head-sampling decision is made here. `remote` is a
    (trace_id, parent_span_id) pair from an incoming traceparent header:
    the new span is a root locally (it owns finish_trace) but continues
    the caller's trace id. Returns None when tracing is disabled."""
    if not enabled():
        return None
    from .core.flags import FLAGS
    if parent is None and remote is None:
        parent = _CURRENT.get()
    if parent is not None:
        sp = Span(parent.trace_id, new_span_id(), parent.span_id, name,
                  t_start=t_start)
        with _LOCK:
            tr = _ACTIVE.get(parent.trace_id)
            if tr is not None:
                tr.spans.append(sp)
    else:
        if remote is not None:
            trace_id, parent_id = remote
        else:
            trace_id, parent_id = new_trace_id(), None
        sp = Span(trace_id, new_span_id(), parent_id, name,
                  t_start=t_start)
        head = random.random() < FLAGS.trace_sample
        with _LOCK:
            tr = _ACTIVE.get(trace_id)
            if remote is not None and tr is not None:
                # The "remote" parent lives in THIS process (in-process
                # router tier -> replica tier): the trace is already
                # active here, so joining must not steal its root —
                # record the hop as an ordinary child span and leave the
                # keep/drop decision with the owning root.
                tr.spans.append(sp)
            else:
                _ACTIVE[trace_id] = _Trace(trace_id, sp, head)
    if attrs:
        sp.attrs.update(attrs)
    STAT_ADD("trace.spans_started")
    return sp


def end_span(span: Optional[Span], error: Optional[str] = None,
             t_end: Optional[float] = None):
    """Close a span (idempotent; None-tolerant). `t_end` is a wall-clock
    override for retroactive closes; the default path uses the monotonic
    perf delta."""
    if span is None or span._done:
        return
    span._done = True
    if t_end is not None:
        span.dur_ms = max(0.0, (t_end - span.t_start) * 1e3)
    else:
        span.dur_ms = (time.perf_counter() - span._perf0) * 1e3
    if error:
        span.status = "error"
        span.attrs.setdefault("error", str(error)[:200])


def record_span(name: str, t_start: float, t_end: float,
                parent: Optional[Span],
                attrs: Optional[dict] = None) -> Optional[Span]:
    """Retroactively record an already-elapsed interval as a closed
    child span (wall-clock endpoints). This is how hot loops attribute
    sub-steps without contextvar churn: measure with plain perf
    counters, record once after the fact."""
    if not enabled() or parent is None:
        return None
    sp = start_span(name, parent=parent, attrs=attrs, t_start=t_start)
    end_span(sp, t_end=t_end)
    return sp


def finish_trace(root: Optional[Span], error: Optional[str] = None,
                 e2e_ms: Optional[float] = None,
                 record_latency: bool = True) -> bool:
    """Close the root span and apply the tail keep rules. Keep when the
    request errored, OR was slower than slow_threshold_ms(), OR won the
    head-sampling coin flip; kept traces move to the bounded ring,
    dropped ones are only counted. Returns the keep decision (False for
    None/unknown roots). Unclosed child spans are force-closed at the
    root's end so an exporter never sees dur_ms=None.
    `record_latency=False` keeps this trace's duration out of the
    rolling tail window (batch-scoped traces must not drag the
    request-latency threshold down)."""
    if root is None:
        return False
    end_span(root, error=error)
    if e2e_ms is None:
        e2e_ms = root.dur_ms
    root.attrs.setdefault("e2e_ms", round(e2e_ms, 3))
    from .core.flags import FLAGS
    with _LOCK:
        tr = _ACTIVE.get(root.trace_id)
        if tr is not None and tr.root is not root:
            # A same-process traceparent join (see start_span): this
            # span is a child of a trace whose root is still open —
            # closing it must not pop the owner's bookkeeping.
            return False
        _ACTIVE.pop(root.trace_id, None)
        thresh = _slow_threshold_locked(FLAGS)
        if record_latency:
            _LAT_WINDOW.append(e2e_ms)
    if tr is None:
        return False
    t_end = root.t_start + (root.dur_ms or 0.0) / 1e3
    for sp in tr.spans:
        if not sp._done:
            end_span(sp, t_end=t_end)
    slow = record_latency and thresh is not None and e2e_ms > thresh
    keep = bool(error) or slow or tr.head_sampled
    if keep:
        if error:
            root.attrs["keep"] = "error"
        elif slow:
            root.attrs["keep"] = "slow"
        else:
            root.attrs["keep"] = "head"
        with _LOCK:
            cap = FLAGS.trace_ring_capacity
            for sp in tr.spans:
                while cap > 0 and len(_RING) >= cap:
                    _RING.popleft()
                _RING.append(sp.to_dict())
            n = len(_RING)
        STAT_ADD("trace.spans_kept", len(tr.spans))
        STAT_SET("trace.ring_spans", n)
    else:
        STAT_ADD("trace.spans_dropped", len(tr.spans))
    return keep


def is_root(span: Optional[Span]) -> bool:
    """True when `span` is the registered root of an in-flight trace
    (i.e. the span whose completion must run the tail keep/drop rules)."""
    if span is None:
        return False
    with _LOCK:
        tr = _ACTIVE.get(span.trace_id)
        return tr is not None and tr.root is span


def complete_request(span: Optional[Span], error: Optional[str] = None,
                     e2e_ms: Optional[float] = None):
    """Request-completion choke point (called from `_Response._complete`
    — the one funnel every success AND failure path of the batcher and
    generation engine flows through). Ends the request span; when the
    span is its trace's root (no HTTP parent wrapping it) this also
    runs finish_trace so the tail sampling decision happens exactly
    once, at the outermost owner."""
    if span is None:
        return
    if is_root(span):
        finish_trace(span, error=error, e2e_ms=e2e_ms)
    else:
        end_span(span, error=error)


def _slow_threshold_locked(FLAGS) -> Optional[float]:
    if FLAGS.trace_tail_slow_ms > 0:
        return FLAGS.trace_tail_slow_ms
    if len(_LAT_WINDOW) < _LAT_MIN_SAMPLES:
        return None
    ordered = sorted(_LAT_WINDOW)
    return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]


def slow_threshold_ms() -> Optional[float]:
    """Current tail 'slow' threshold: FLAGS_trace_tail_slow_ms when set,
    else a rolling p95 of recent e2e latencies (None until
    _LAT_MIN_SAMPLES requests have finished)."""
    from .core.flags import FLAGS
    with _LOCK:
        return _slow_threshold_locked(FLAGS)


# ---------------------------------------------------------------------------
# Context propagation
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def use_span(span: Optional[Span]):
    """Make `span` the contextvar-current span for the scope. This is
    the thread hand-off primitive: the submitting thread stashes the
    Span on the queue entry, the worker re-enters it here. No-op for
    None, so call sites need no enabled() guard."""
    if span is None:
        yield None
        return
    tok = _CURRENT.set(span)
    try:
        yield span
    finally:
        _CURRENT.reset(tok)


@contextlib.contextmanager
def span(name: str, attrs: Optional[dict] = None):
    """start_span + use_span + end_span in one scope; errors mark the
    span and re-raise."""
    sp = start_span(name, attrs=attrs)
    if sp is None:
        yield None
        return
    tok = _CURRENT.set(sp)
    try:
        yield sp
    except BaseException as e:  # noqa: BLE001 — status only; re-raised
        end_span(sp, error=f"{type(e).__name__}: {e}")
        raise
    finally:
        _CURRENT.reset(tok)
        end_span(sp)


# ---------------------------------------------------------------------------
# W3C traceparent (00-<trace_id>-<span_id>-<flags>)
# ---------------------------------------------------------------------------

def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) from a W3C traceparent header, or None for
    anything malformed (bad version, wrong field widths, non-hex,
    all-zero ids — per the spec these must be ignored, not propagated)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    ver, trace_id, span_id, _flags = parts
    if len(ver) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    if ver == "ff":
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
        int(_flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(span: Span, sampled: bool = True) -> str:
    return f"00-{span.trace_id}-{span.span_id}-{'01' if sampled else '00'}"


# ---------------------------------------------------------------------------
# Ring access + export
# ---------------------------------------------------------------------------

def ring_spans() -> List[dict]:
    """Point-in-time copy of the kept-span ring (oldest first)."""
    with _LOCK:
        return list(_RING)


def spans_for_trace_ids(trace_ids) -> List[dict]:
    """Kept-ring spans belonging to any of `trace_ids`, ring order
    (oldest first). This is the exemplar -> incident-bundle linkage:
    a histogram exemplar in a breaching bucket is a trace_id, and the
    alert engine (monitor_alerts.py) pulls the full trace behind it
    into the bundle with this."""
    want = set(trace_ids)
    if not want:
        return []
    with _LOCK:
        return [s for s in _RING if s.get("trace_id") in want]


def drain_spans() -> List[dict]:
    """Copy-and-clear the ring (exporters call this so a periodic dump
    never writes a span twice)."""
    with _LOCK:
        out = list(_RING)
        _RING.clear()
    STAT_SET("trace.ring_spans", 0)
    return out


def export_jsonl(path: str, spans: Optional[List[dict]] = None) -> int:
    """Append kept spans as JSONL (one `kind="span"` record per line,
    same append-mode crash-safety contract as snapshot_to_jsonl).
    Defaults to drain_spans(). Returns #spans written."""
    if spans is None:
        spans = drain_spans()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        for sp in spans:
            f.write(json.dumps(sp) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return len(spans)


def export_chrome_tracing(path: str,
                          spans: Optional[List[dict]] = None,
                          include_phases: bool = True) -> int:
    """Dump spans as chrome://tracing complete events, merged with the
    monitor's host-phase events (one timeline: request spans on their
    trace rows, host phases on their thread rows). Returns #events."""
    if spans is None:
        spans = ring_spans()
    pid = os.getpid()
    events = []
    for sp in spans:
        events.append({
            "name": sp["name"], "ph": "X",
            "ts": sp["t_start"] * 1e6,
            "dur": (sp["dur_ms"] or 0.0) * 1e3,
            "pid": pid, "tid": f"trace:{sp['trace_id'][:8]}",
            "args": {"trace_id": sp["trace_id"],
                     "span_id": sp["span_id"],
                     "parent_id": sp["parent_id"],
                     "status": sp["status"], **sp["attrs"]}})
    if include_phases:
        for nm, ts_us, dur_us, tid in monitor.phase_events():
            events.append({"name": nm, "ph": "X", "ts": ts_us,
                           "dur": dur_us, "pid": pid, "tid": tid})
    trace = {"displayTimeUnit": "ms", "traceEvents": events}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(events)


def reset():
    """Drop every in-flight trace, the kept ring, and the rolling
    latency window (tests)."""
    with _LOCK:
        _ACTIVE.clear()
        _RING.clear()
        _LAT_WINDOW.clear()

"""Checkpointing + inference-model export.

Reference: python/paddle/fluid/io.py — save_vars/save_persistables emit
save ops (operators/save_op.cc); save_inference_model prunes to the
feed→fetch subgraph (io.py:997). Here persistence is host-side (numpy .npz
per-var files, program JSON) — the wire format is ours, the semantics match:
save/load_persistables round-trips training state, save/load_inference_model
exports a pruned program + params that Executor.run can serve directly.
Sharded (orbax-style) checkpoints for multi-host land with the fleet path.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .core.scope import global_scope
from .framework import Program, Variable
from .reader import DataLoader, PyReader  # noqa: F401  (fluid.io.DataLoader)

__all__ = ["DataLoader", "PyReader",
           "save_vars", "save_params", "save_persistables", "load_vars",
           "load_params", "load_persistables", "save_inference_model",
           "load_inference_model", "save", "load", "batch"]


def _var_path(dirname, name):
    return os.path.join(dirname, name.replace("/", "%2F"))


# Atomic write helpers: every checkpoint artifact is written to a
# pid-suffixed temp file, fsynced, then os.replace-d over the target, so
# a crash or preemption mid-save can never leave a half-written file
# that a later load_* accepts — the reader sees either the previous
# complete checkpoint or the new complete one. The file-object form of
# np.save/np.savez is deliberate: the string-path form appends
# .npy/.npz to the name, which is how save() used to write
# `x.pdparams.npz` while load() read `x.pdparams`.

def atomic_np_save(path: str, arr) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_np_savez(path: str, blob: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from .framework import default_main_program
    program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in program.list_vars() if predicate is None or
                predicate(v)]
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    if filename is not None:
        blob = {}
        for v in vars:
            if scope.has(v.name):
                blob[v.name] = scope.get_numpy(v.name)
        atomic_np_savez(os.path.join(dirname, filename), blob)
        return
    for v in vars:
        if scope.has(v.name):
            atomic_np_save(_var_path(dirname, v.name) + ".npy",
                           scope.get_numpy(v.name))


def _is_persistable(v: Variable):
    return v.persistable and not v.is_data


def _is_param(v: Variable):
    return v.is_parameter


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, None, _is_param,
                     filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program, None, _is_persistable,
                     filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from .framework import default_main_program
    program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in program.list_vars() if predicate is None or
                predicate(v)]
    scope = global_scope()
    if filename is not None:
        blob = np.load(os.path.join(dirname, filename))
        for v in vars:
            if v.name in blob:
                scope.set(v.name, blob[v.name])
        return
    for v in vars:
        path = _var_path(dirname, v.name) + ".npy"
        if os.path.exists(path):
            scope.set(v.name, np.load(path))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, None, _is_param,
                     filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, None, _is_persistable,
                     filename)


def _prune_for_inference(program: Program, feed_names: List[str],
                         fetch_names: List[str]) -> Program:
    """Keep only ops needed to compute fetches from feeds
    (reference: framework/prune.cc + Program._prune)."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block()
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        out_names = set(op.output_names())
        if out_names & needed:
            keep.append(op)
            for n in op.input_names():
                needed.add(n)
    keep.reverse()
    block.ops = keep
    # Drop vars no kept op touches (e.g. optimizer accumulators) so the
    # export doesn't carry training state (reference prune.cc behavior).
    referenced = set(feed_names) | set(fetch_names)
    for op in keep:
        referenced.update(op.input_names())
        referenced.update(op.output_names())
    block.vars = {n: v for n, v in block.vars.items() if n in referenced}
    pruned._fp_cache = None
    return pruned


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    from .framework import default_main_program
    program = main_program or default_main_program()
    fetch_names = [v.name if isinstance(v, Variable) else v
                   for v in target_vars]
    pruned = _prune_for_inference(program, list(feeded_var_names),
                                  fetch_names)
    os.makedirs(dirname, exist_ok=True)
    meta = {"program": pruned.to_dict(), "feed_names": list(feeded_var_names),
            "fetch_names": fetch_names}
    atomic_write_text(
        os.path.join(dirname, model_filename or "__model__.json"),
        json.dumps(meta))
    if not program_only:
        save_persistables(executor, dirname, pruned,
                          filename=params_filename)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname, model_filename or "__model__.json")) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    load_persistables(executor, dirname, program, filename=params_filename)
    block = program.global_block()
    fetch_vars = [block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


def save(program, model_path):
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    scope = global_scope()
    blob = {v.name: scope.get_numpy(v.name)
            for v in program.list_vars()
            if v.persistable and scope.has(v.name)}
    atomic_np_savez(model_path + ".pdparams", blob)
    atomic_write_text(model_path + ".pdmodel", program.to_json())


def load(program, model_path, executor=None):
    path = model_path + ".pdparams"
    if not os.path.exists(path) and os.path.exists(path + ".npz"):
        path += ".npz"  # checkpoint written before the atomic rewrite
    blob = np.load(path)
    scope = global_scope()
    for name in blob.files:
        scope.set(name, blob[name])


def batch(reader, batch_size, drop_last=False):
    """reference fluid.io.batch / paddle.batch decorator."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched

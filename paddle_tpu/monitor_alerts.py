"""SLO alerting over the monitor registry (the Monarch/Prometheus
alerting layer on top of monitor.py's point-in-time stats).

Rules are declared in ``FLAGS_alert_rules`` (semicolon-separated) and
evaluated against ``monitor.get_stats_snapshot()`` — either by the
background evaluator thread (``maybe_start()``, period
``FLAGS_alert_eval_interval_s``) or explicitly via
``AlertEngine.evaluate_once(now=...)``, which tests drive with a fake
clock. Three rule kinds:

- ``name:threshold:STAT OP VALUE[:for=DUR]`` — a counter/gauge compared
  against a constant; with ``for=`` the breach must hold continuously
  (pending state) before the rule fires.
- ``name:ratio:NUM/DEN OP VALUE[:for=DUR]`` — the ratio of two counters
  (error rate = ``serving.rejected/serving.requests``); a zero
  denominator never breaches.
- ``name:burn:HIST:pQQ OP VALUE:windows=W1,W2[,...]`` — multi-window
  burn rate over a histogram percentile. Each tick appends the
  histogram's cumulative bucket counts to a per-rule history ring; the
  windowed percentile is computed over the COUNT DELTA between now and
  the newest sample at least W old. The rule breaches only when EVERY
  window breaches — a one-tick latency spike trips the short window but
  is diluted out of the long one, so only a sustained breach fires
  (classic multi-window burn-rate alerting). A window without full
  history coverage never breaches (cold-start guard).

State machine per rule: inactive -> pending (breach seen, ``for=`` not
yet satisfied) -> firing -> inactive (resolved). On the transition INTO
firing the engine writes exactly one **incident bundle** (when
``FLAGS_alert_bundle_dir`` is set): a single atomic JSON file
correlating the rule, the full stats snapshot, trace exemplars from the
breaching histogram buckets (breaching buckets first), the kept-trace
ring, and the flight-recorder ring — everything a post-mortem needs in
one artifact, written tmp+fsync+rename like dump_flight_recorder.

Exposure: ``alertz_dict()`` backs the serving/router ``/alertz``
endpoints, ``prometheus_alerts_text()`` appends Prometheus
``ALERTS{alertname=...,alertstate=...}`` series to
``monitor.prometheus_text()``, ``firing_count()`` rides along in
``/healthz`` detail (alerts inform — they never flip health state).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .monitor import (STAT_ADD, STAT_SET, flight_records,
                      get_stats_snapshot)

__all__ = [
    "AlertEngine", "AlertRule", "parse_rules", "parse_duration",
    "maybe_start", "stop_alerts", "get_engine", "active_engine",
    "firing_count", "alertz_dict", "prometheus_alerts_text",
]

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


def parse_duration(s: str) -> float:
    """'30s' / '5m' / '1h' / bare seconds -> seconds (float)."""
    s = s.strip()
    if not s:
        raise ValueError("empty duration")
    mult = 1.0
    if s[-1] in "smh":
        mult = {"s": 1.0, "m": 60.0, "h": 3600.0}[s[-1]]
        s = s[:-1]
    return float(s) * mult


def _parse_cmp(expr: str):
    """'LHS OP VALUE' -> (lhs, op, value). Longest-op-first so '>='
    never parses as '>'."""
    for op in (">=", "<=", ">", "<"):
        if op in expr:
            lhs, rhs = expr.split(op, 1)
            return lhs.strip(), op, float(rhs.strip())
    raise ValueError(f"no comparison operator in {expr!r}")


class AlertRule:
    """One parsed rule. kind is 'threshold' | 'ratio' | 'burn'."""
    __slots__ = ("name", "kind", "stat", "num", "den", "pct", "op",
                 "value", "for_s", "windows_s", "expr")

    def __init__(self, name, kind, op, value, expr, stat=None, num=None,
                 den=None, pct=None, for_s=0.0, windows_s=()):
        self.name = name
        self.kind = kind
        self.op = op
        self.value = value
        self.expr = expr
        self.stat = stat
        self.num = num
        self.den = den
        self.pct = pct
        self.for_s = for_s
        self.windows_s = tuple(windows_s)

    def to_dict(self) -> dict:
        d = {"name": self.name, "kind": self.kind, "expr": self.expr,
             "op": self.op, "threshold": self.value}
        if self.kind == "burn":
            d["histogram"] = self.stat
            d["percentile"] = self.pct
            d["windows_s"] = list(self.windows_s)
        elif self.kind == "ratio":
            d["numerator"] = self.num
            d["denominator"] = self.den
        else:
            d["stat"] = self.stat
        if self.for_s:
            d["for_s"] = self.for_s
        return d


def parse_rules(spec: str) -> List["AlertRule"]:
    """Parse the FLAGS_alert_rules grammar. Raises ValueError with the
    offending rule text on any malformed entry."""
    rules: List[AlertRule] = []
    seen = set()
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = [p.strip() for p in raw.split(":")]
        if len(parts) < 3:
            raise ValueError(f"bad alert rule {raw!r}: expected "
                             "name:kind:expr[...]")
        name, kind = parts[0], parts[1]
        if not name or name in seen:
            raise ValueError(f"bad alert rule {raw!r}: empty or "
                             "duplicate rule name")
        seen.add(name)
        try:
            if kind == "threshold":
                lhs, op, value = _parse_cmp(parts[2])
                for_s = _parse_opts(parts[3:], raw, allow_for=True)
                rules.append(AlertRule(
                    name, kind, op, value, parts[2], stat=lhs,
                    for_s=for_s))
            elif kind == "ratio":
                lhs, op, value = _parse_cmp(parts[2])
                if "/" not in lhs:
                    raise ValueError("ratio needs NUM/DEN")
                num, den = (s.strip() for s in lhs.split("/", 1))
                for_s = _parse_opts(parts[3:], raw, allow_for=True)
                rules.append(AlertRule(
                    name, kind, op, value, parts[2], num=num, den=den,
                    for_s=for_s))
            elif kind == "burn":
                if len(parts) < 5:
                    raise ValueError(
                        "burn needs name:burn:HIST:pQQ OP V:windows=...")
                hist = parts[2]
                lhs, op, value = _parse_cmp(parts[3])
                if not lhs.startswith("p"):
                    raise ValueError(f"bad percentile {lhs!r}")
                pct = float(lhs[1:]) / 100.0
                if not 0.0 < pct <= 1.0:
                    raise ValueError(f"percentile out of range: {lhs}")
                windows = ()
                for opt in parts[4:]:
                    if opt.startswith("windows="):
                        windows = tuple(
                            parse_duration(w)
                            for w in opt[len("windows="):].split(","))
                    else:
                        raise ValueError(f"unknown option {opt!r}")
                if len(windows) < 1:
                    raise ValueError("burn rule needs windows=W1[,W2]")
                rules.append(AlertRule(
                    name, kind, op, value, raw, stat=hist, pct=pct,
                    windows_s=windows))
            else:
                raise ValueError(f"unknown rule kind {kind!r}")
        except ValueError as e:
            raise ValueError(f"bad alert rule {raw!r}: {e}") from None
    return rules


def _parse_opts(opts, raw, allow_for=False) -> float:
    for_s = 0.0
    for opt in opts:
        if allow_for and opt.startswith("for="):
            for_s = parse_duration(opt[len("for="):])
        else:
            raise ValueError(f"unknown option {opt!r}")
    return for_s


def _delta_percentile(bounds, counts_delta, q, max_hint):
    """monitor._Histogram.percentile over a windowed count delta.
    `bounds` excludes the overflow bucket; `max_hint` (the histogram's
    all-time max) stands in for the unknown window max when the target
    lands in overflow."""
    total = sum(counts_delta)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    lo = 0.0
    for i, c in enumerate(counts_delta):
        hi = bounds[i] if i < len(bounds) else max_hint
        if cum + c >= target and c > 0:
            frac = (target - cum) / c
            return min(lo + (hi - lo) * frac, max_hint)
        cum += c
        lo = hi
    return max_hint


class _RuleState:
    __slots__ = ("state", "since", "fired_at", "resolved_at", "value",
                 "last_eval", "bundle_path", "windows")

    def __init__(self):
        self.state = "inactive"
        self.since = None        # first breach ts of the current episode
        self.fired_at = None
        self.resolved_at = None
        self.value = None        # last computed rule value
        self.last_eval = None
        self.bundle_path = None  # bundle of the current/last firing
        self.windows = None      # burn rules: per-window detail dict


class AlertEngine:
    """Evaluates a rule list against the live monitor registry. One
    engine per process (module singleton below); tests construct their
    own with a fake `clock`."""

    def __init__(self, rules: Optional[List[AlertRule]] = None,
                 clock=time.time):
        if rules is None:
            from .core.flags import FLAGS
            rules = parse_rules(FLAGS.alert_rules)
        self.rules = rules
        self._clock = clock
        self._lock = threading.Lock()
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in rules}
        # burn rules: rule name -> deque[(ts, counts_list, max_hint)]
        self._hist_history: Dict[str, deque] = {
            r.name: deque() for r in rules if r.kind == "burn"}

    # -- evaluation ------------------------------------------------------

    def evaluate_once(self, now: Optional[float] = None) -> dict:
        """One evaluation tick over a single registry snapshot. Returns
        the alertz dict (also what /alertz serves)."""
        now = self._clock() if now is None else now
        snap = get_stats_snapshot()
        with self._lock:
            for rule in self.rules:
                value, breach = self._eval_rule(rule, snap, now)
                st = self._state[rule.name]
                st.value = value
                st.last_eval = now
                if breach:
                    if st.state == "inactive":
                        st.since = now
                        if rule.for_s > 0:
                            st.state = "pending"
                        else:
                            self._fire(rule, st, snap, now)
                    elif st.state == "pending" and \
                            now - st.since >= rule.for_s:
                        self._fire(rule, st, snap, now)
                else:
                    if st.state == "firing":
                        st.resolved_at = now
                        STAT_ADD("alerts.resolved")
                    st.state = "inactive"
                    st.since = None
            firing = sum(1 for s in self._state.values()
                         if s.state == "firing")
            pending = sum(1 for s in self._state.values()
                          if s.state == "pending")
            out = self._to_dict_locked(now)
        STAT_ADD("alerts.evals")
        STAT_SET("alerts.firing", firing)
        STAT_SET("alerts.pending", pending)
        return out

    def _eval_rule(self, rule, snap, now):
        if rule.kind == "threshold":
            v = snap["gauges"].get(rule.stat)
            if v is None:
                v = snap["counters"].get(rule.stat)
            if v is None:
                return None, False
            return v, _OPS[rule.op](v, rule.value)
        if rule.kind == "ratio":
            num = snap["counters"].get(rule.num, 0)
            den = snap["counters"].get(rule.den, 0)
            if den <= 0:
                return None, False
            v = num / den
            return v, _OPS[rule.op](v, rule.value)
        return self._eval_burn(rule, snap, now)

    def _eval_burn(self, rule, snap, now):
        hist = snap["histograms"].get(rule.stat)
        history = self._hist_history[rule.name]
        if hist is None:
            history.clear()  # histogram was reset: old counts are stale
            self._state[rule.name].windows = None
            return None, False
        # buckets dict is insertion-ordered (bucket order, +inf last)
        counts = list(hist["buckets"].values())
        bounds = [float(k) for k in hist["buckets"] if k != "+inf"]
        max_hint = hist["max"] if hist["max"] is not None else 0.0
        if history and sum(counts) < sum(history[-1][1]):
            history.clear()  # STAT_RESET under us
        history.append((now, counts, max_hint))
        horizon = now - max(rule.windows_s) - 1.0
        while len(history) > 1 and history[1][0] <= horizon:
            history.popleft()
        windows = {}
        breach_all = True
        value = None
        for w in sorted(rule.windows_s):
            base = None
            for ts, c, _m in reversed(history):
                if ts <= now - w:
                    base = c
                    break
            if base is None:
                # no sample old enough: window lacks full coverage
                windows[f"{w:g}s"] = {"p": None, "covered": False}
                breach_all = False
                continue
            delta = [a - b for a, b in zip(counts, base)]
            p = _delta_percentile(bounds, delta, rule.pct, max_hint)
            breach = p is not None and _OPS[rule.op](p, rule.value)
            windows[f"{w:g}s"] = {"p": p, "covered": True,
                                  "breach": breach}
            if value is None:
                value = p  # report the shortest window's percentile
            if not breach:
                breach_all = False
        self._state[rule.name].windows = windows
        return value, breach_all and len(windows) > 0

    # -- firing + incident bundles ---------------------------------------

    def _fire(self, rule, st, snap, now):
        st.state = "firing"
        st.fired_at = now
        st.resolved_at = None
        STAT_ADD("alerts.fired")
        st.bundle_path = self._write_bundle(rule, st, snap, now)

    def _write_bundle(self, rule, st, snap, now) -> Optional[str]:
        """Exactly one atomic incident bundle per pending->firing
        transition. Returns the path, or None when bundling is off or
        the write failed (a bundle failure must never unwind the
        evaluator)."""
        from .core.flags import FLAGS
        d = FLAGS.alert_bundle_dir
        if not d:
            return None
        try:
            from . import trace
            exemplar_ids = self._breaching_exemplars(rule, snap)
            ring = trace.ring_spans()
            linked = trace.spans_for_trace_ids(exemplar_ids)
            linked_keys = {(s.get("trace_id"), s.get("span_id"))
                           for s in linked}
            cap = max(0, FLAGS.alert_bundle_max_spans)
            spans = list(linked)[:cap]
            # newest kept spans fill the remainder of the budget
            for sp in reversed(ring):
                if len(spans) >= cap:
                    break
                if (sp.get("trace_id"), sp.get("span_id")) \
                        not in linked_keys:
                    spans.append(sp)
            bundle = {
                "kind": "incident_bundle",
                "ts": now,
                "pid": os.getpid(),
                "rule": rule.to_dict(),
                "state": "firing",
                "value": st.value,
                "windows": st.windows,
                "snapshot": snap,
                "exemplar_trace_ids": exemplar_ids,
                "spans": spans,
                "n_spans_dropped": max(0, len(ring) - len(spans)),
                "flight_records": flight_records(),
            }
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"incident_{rule.name}_{int(now * 1000)}.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(bundle, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            STAT_ADD("alerts.bundles_written")
            return path
        except Exception:  # noqa: BLE001 — alerting must not crash the
            STAT_ADD("alerts.bundle_errors")  # process it watches
            return None

    def _breaching_exemplars(self, rule, snap) -> List[str]:
        """Trace exemplars pulled from the rule's histogram, breaching
        buckets first (bounds above the threshold, worst first), then
        the rest — so the first ids in the bundle are requests that
        actually breached the SLO."""
        if rule.kind != "burn":
            return []
        hist = snap["histograms"].get(rule.stat)
        if not hist or "exemplars" not in hist:
            return []
        breaching, rest = [], []
        for key, ex in hist["exemplars"].items():
            bound = float("inf") if key == "+inf" else float(key)
            (breaching if bound > rule.value else rest).append(
                (bound, ex))
        out, seen = [], set()
        for _b, ex in (sorted(breaching, reverse=True) + sorted(rest)):
            if ex not in seen:
                seen.add(ex)
                out.append(ex)
        return out

    # -- exposure --------------------------------------------------------

    def _to_dict_locked(self, now) -> dict:
        rules = []
        for rule in self.rules:
            st = self._state[rule.name]
            r = rule.to_dict()
            r.update({"state": st.state, "value": st.value,
                      "since": st.since, "fired_at": st.fired_at,
                      "resolved_at": st.resolved_at,
                      "last_eval": st.last_eval})
            if st.windows is not None:
                r["window_detail"] = st.windows
            if st.bundle_path:
                r["bundle"] = st.bundle_path
            rules.append(r)
        return {"ts": now,
                "firing": sum(1 for s in self._state.values()
                              if s.state == "firing"),
                "pending": sum(1 for s in self._state.values()
                               if s.state == "pending"),
                "rules": rules}

    def to_dict(self) -> dict:
        with self._lock:
            return self._to_dict_locked(self._clock())

    def firing(self) -> List[str]:
        with self._lock:
            return [r.name for r in self.rules
                    if self._state[r.name].state == "firing"]

    def firing_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._state.values()
                       if s.state == "firing")

    def prometheus_text(self) -> str:
        """Prometheus ALERTS exposition: one series per non-inactive
        rule, matching what a Prometheus server derives from alerting
        rules — so dashboards built on ALERTS{} work unchanged."""
        out = []
        with self._lock:
            for rule in self.rules:
                st = self._state[rule.name]
                if st.state == "inactive":
                    continue
                out.append(
                    f'ALERTS{{alertname="{rule.name}",'
                    f'alertstate="{st.state}"}} 1')
        if not out:
            return ""
        return "\n".join(["# TYPE ALERTS gauge"] + out) + "\n"


# ---------------------------------------------------------------------------
# Module singleton + background evaluator
# ---------------------------------------------------------------------------

_ENGINE: Optional[AlertEngine] = None
_ENGINE_LOCK = threading.Lock()
_THREAD: Optional[threading.Thread] = None
_STOP = threading.Event()


def active_engine() -> Optional[AlertEngine]:
    """The running singleton, or None — never creates one (cheap enough
    for /healthz and scrape paths)."""
    return _ENGINE


def get_engine() -> Optional[AlertEngine]:
    """Singleton from FLAGS_alert_rules (None when no rules are set).
    Does not start the background thread — maybe_start() does."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is not None:
            return _ENGINE
        from .core.flags import FLAGS
        if not FLAGS.alert_rules:
            return None
        _ENGINE = AlertEngine()
        return _ENGINE


def maybe_start() -> Optional[AlertEngine]:
    """Idempotently start the background evaluator. No-op (returns
    None) when FLAGS_alert_rules is empty; with
    FLAGS_alert_eval_interval_s <= 0 the engine exists but only
    evaluates when evaluate_once() is called explicitly."""
    global _THREAD
    eng = get_engine()
    if eng is None:
        return None
    from .core.flags import FLAGS
    interval = FLAGS.alert_eval_interval_s
    with _ENGINE_LOCK:
        if interval > 0 and (_THREAD is None or not _THREAD.is_alive()):
            _STOP.clear()

            def loop():
                while not _STOP.wait(interval):
                    try:
                        eng.evaluate_once()
                    except Exception:  # noqa: BLE001 — keep evaluating
                        pass
            _THREAD = threading.Thread(
                target=loop, name="ptn-alert-eval", daemon=True)
            _THREAD.start()
    return eng


def stop_alerts():
    """Stop the evaluator thread and drop the singleton (tests call
    this between cases; flag changes take effect on the next start)."""
    global _ENGINE, _THREAD
    _STOP.set()
    t = _THREAD
    if t is not None and t.is_alive():
        t.join(timeout=5.0)
    with _ENGINE_LOCK:
        _ENGINE = None
        _THREAD = None


def firing_count() -> int:
    eng = _ENGINE
    return eng.firing_count() if eng is not None else 0


def alertz_dict() -> dict:
    """What /alertz serves. An engine-less process still answers with
    an empty rule list so probes need no special-casing."""
    eng = _ENGINE
    if eng is None:
        return {"ts": time.time(), "firing": 0, "pending": 0,
                "rules": []}
    return eng.to_dict()


def prometheus_alerts_text() -> str:
    eng = _ENGINE
    return eng.prometheus_text() if eng is not None else ""

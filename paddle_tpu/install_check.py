"""fluid.install_check.run_check equivalent (reference install_check.py):
train a tiny fc for one step on the default device, then once more under
the data-parallel compiled path."""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    from . import (CompiledProgram, Executor, Program, layers, optimizer,
                   program_guard)

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("install_check_x", shape=[2], dtype="float32")
        y = layers.fc(x, size=1)
        loss = layers.mean(y)
        optimizer.SGD(learning_rate=0.01).minimize(loss)

    exe = Executor()
    exe.run(startup)
    feed = {"install_check_x": np.ones((4, 2), np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss])
    compiled = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    exe.run(compiled, feed=feed, fetch_list=[loss])
    print("Your paddle_tpu works well on this machine.")

"""Program visualization + introspection.

Reference: python/paddle/fluid/debugger.py draw_block_graphviz (+ the
C++ ir/graph_viz_pass.cc pass that dumps .dot per graph), and
platform/lodtensor_printer.cc (fetch-var printing — here layers.Print /
the `print` op carry that role via jax.debug.print).
"""
from __future__ import annotations

__all__ = ["draw_block_graphviz", "program_to_dot", "pprint_program"]


def _esc(s):
    return str(s).replace('"', '\\"')


def program_to_dot(program, block_idx=0, skip_vars=()) -> str:
    """Render one block as graphviz dot text: op nodes (boxes) wired to
    var nodes (ellipses); parameters shaded."""
    block = program.blocks[block_idx]
    lines = ["digraph G {", "  rankdir=TB;",
             '  node [fontsize=10, fontname="Helvetica"];']
    var_ids = {}

    def var_node(name):
        if name in var_ids or name in skip_vars:
            return var_ids.get(name)
        vid = f"var_{len(var_ids)}"
        var_ids[name] = vid
        v = block._find_var_recursive(name)
        shape = getattr(v, "shape", None) if v is not None else None
        style = 'style=filled, fillcolor="#c0d0f0"' \
            if v is not None and v.is_parameter else \
            'style=filled, fillcolor="#eeeeee"'
        lines.append(
            f'  {vid} [label="{_esc(name)}\\n{_esc(shape)}", '
            f"shape=ellipse, {style}];")
        return vid

    for i, op in enumerate(block.ops):
        oid = f"op_{i}"
        lines.append(
            f'  {oid} [label="{_esc(op.type)}", shape=box, '
            f'style=filled, fillcolor="#f0d0c0"];')
        for names in op.inputs.values():
            for n in names:
                if n and n not in skip_vars:
                    lines.append(f"  {var_node(n)} -> {oid};")
        for names in op.outputs.values():
            for n in names:
                if n and n not in skip_vars:
                    lines.append(f"  {oid} -> {var_node(n)};")
    lines.append("}")
    return "\n".join(lines)


def draw_block_graphviz(block, highlights=None, path="program.dot"):
    """Reference-compatible entry (debugger.py draw_block_graphviz):
    writes dot text for `block` to `path`."""
    dot = program_to_dot(block.program, block.idx)
    with open(path, "w") as f:
        f.write(dot)
    return path


def pprint_program(program, file=None) -> str:
    """Human-readable op listing per block (the reference's
    Program.to_string analogue for quick debugging)."""
    out = []
    for blk in program.blocks:
        out.append(f"block {blk.idx} (parent {blk.parent_idx}):")
        for op in blk.ops:
            ins = {s: n for s, n in op.inputs.items() if n}
            outs = {s: n for s, n in op.outputs.items() if n}
            out.append(f"  {op.type}({ins}) -> {outs}")
    text = "\n".join(out)
    if file is not None:
        print(text, file=file)
    return text

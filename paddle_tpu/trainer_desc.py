"""Trainer + DeviceWorker configuration layer for the dataset path.

Reference: python/paddle/fluid/trainer_desc.py (TrainerDesc/MultiTrainer/
DistMultiTrainer/PipelineTrainer building trainer_desc.proto),
device_worker.py (Hogwild/DownpourSGD/Section), trainer_factory.py:26 —
configs consumed by C++ TrainerBase/DeviceWorker (trainer.h:38-160,
device_worker.h:103-271).

TPU redesign: the HogwildWorker thread pool collapses into the single
jitted XLA step (device parallelism belongs to XLA), so a "trainer" here
is the host-side loop strategy around that step:

- MultiTrainer: plain loop over dataset batches.
- DistMultiTrainer: + PS liveness (heartbeat PING per period, COMPLETED
  at exit) so the pserver's HeartBeatMonitor sees this worker; the
  push/pull itself lives in the transpiled program's send/recv ops.
- PipelineTrainer: drives parallel.SectionPipeline over section stages
  (trainer.h:115 scope-queue pipeline re-expressed).
"""
from __future__ import annotations

import numpy as np

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer",
           "PipelineTrainer", "DeviceWorker", "Hogwild", "DownpourSGD",
           "Section", "TrainerFactory"]


class DeviceWorker:
    """Reference device_worker.py DeviceWorker base."""

    def __init__(self):
        self._program = None

    def _set_program(self, program):
        self._program = program


class Hogwild(DeviceWorker):
    """device_worker.h:151 HogwildWorker — the default dense worker."""


class DownpourSGD(DeviceWorker):
    """device_worker.h:180 DownpourWorker — sparse PS push/pull; on TPU
    the pulls/pushes are the program's own distributed_lookup_table /
    send ops, so this worker only tags the trainer as PS-attached."""


class Section(DeviceWorker):
    """device_worker.h:271 SectionWorker — one pipeline stage."""

    def __init__(self, section_programs=None):
        super().__init__()
        self.section_programs = section_programs or []


class TrainerDesc:
    def __init__(self):
        self._device_worker = Hogwild()
        self._fetch_vars = []
        self._fetch_info = []
        self._print_period = 100

    def set_device_worker(self, worker):
        self._device_worker = worker

    def set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self._fetch_vars = list(fetch_vars or [])
        self._fetch_info = list(fetch_info or
                                [getattr(v, "name", str(v))
                                 for v in self._fetch_vars])
        self._print_period = print_period

    # -- host loop -------------------------------------------------------
    def run(self, exe, program, dataset, scope=None, drop_last=True):
        from .framework import Variable
        names = [v.name if isinstance(v, Variable) else str(v)
                 for v in self._fetch_vars]
        step, last = 0, []
        self._begin(program)
        try:
            for feed in dataset.batches(drop_last=drop_last):
                last = exe.run(program, feed=feed,
                               fetch_list=list(self._fetch_vars),
                               scope=scope)
                step += 1
                if names and step % self._print_period == 0:
                    msg = ", ".join(
                        f"{i}={np.asarray(v).mean():.6f}"
                        for i, v in zip(self._fetch_info, last))
                    print(f"step {step}: {msg}")
                self._tick(step)
        finally:
            self._end()
        return last

    def _begin(self, program):
        pass

    def _tick(self, step):
        pass

    def _end(self):
        pass


class MultiTrainer(TrainerDesc):
    """trainer.h:64 MultiTrainer."""


class DistMultiTrainer(TrainerDesc):
    """trainer.h:84 DistMultiTrainer: PS-attached loop. Pings the
    pserver heartbeat monitor (heart_beat_monitor.h:54) every
    print_period steps and reports COMPLETED on exit."""

    def __init__(self, endpoints=None, trainer_id=0):
        super().__init__()
        self.endpoints = list(endpoints or [])
        self.trainer_id = trainer_id

    def _client(self):
        from .distributed.rpc import RPCClient
        return RPCClient.instance(self.trainer_id)

    def _begin(self, program):
        for ep in self.endpoints:
            try:
                self._client().ping(ep)
            except Exception:
                pass

    def _tick(self, step):
        if step % max(self._print_period, 1) == 0:
            for ep in self.endpoints:
                try:
                    self._client().ping(ep)
                except Exception:
                    pass

    def _end(self):
        for ep in self.endpoints:
            try:
                self._client().send_complete(ep)
            except Exception:
                pass


class PipelineTrainer(TrainerDesc):
    """trainer.h:115 PipelineTrainer over Section workers. Expects the
    device worker to carry section stage callables/params for
    parallel.SectionPipeline; the IR route (PipelineOptimizer) drives
    this automatically."""

    def run(self, exe, program, dataset, scope=None, drop_last=True):
        if not isinstance(self._device_worker, Section) or \
                not self._device_worker.section_programs:
            raise ValueError(
                "PipelineTrainer needs a Section device worker with "
                "section_programs (use PipelineOptimizer, or pass the "
                "stage programs explicitly)")
        return super().run(exe, program, dataset, scope, drop_last)


class TrainerFactory:
    """trainer_factory.py:26 — picks the trainer from program opt info
    (program._fleet_opt / _pipeline_opt set by fleet/PipelineOptimizer)."""

    def _create_trainer(self, opt_info=None):
        opt_info = opt_info or {}
        name = opt_info.get("trainer", "MultiTrainer")
        worker = opt_info.get("device_worker", "Hogwild")
        t = {"MultiTrainer": MultiTrainer,
             "DistMultiTrainer": DistMultiTrainer,
             "PipelineTrainer": PipelineTrainer}[name]()
        if worker == "Section":
            w = Section(opt_info.get("section_programs"))
        else:
            w = {"Hogwild": Hogwild, "DownpourSGD": DownpourSGD}[worker]()
        if isinstance(t, DistMultiTrainer):
            t.endpoints = list(opt_info.get("endpoints", []))
            t.trainer_id = opt_info.get("trainer_id", 0)
        t.set_device_worker(w)
        return t

"""Weight-decay regularizers (reference: regularizer.py L1/L2Decay) —
applied by Optimizer.apply_gradients as grad := grad + d(reg)/d(param)."""
from __future__ import annotations

from .layers import math_ops

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer"]


class L2DecayRegularizer:
    def __init__(self, regularization_coeff=0.0):
        self.coeff = regularization_coeff

    def append_regularization_op(self, param, grad):
        from .layers.nn import scale
        from .layers.math_ops import elementwise_add
        decay = scale(param, scale=self.coeff)
        return elementwise_add(grad, decay)


class L1DecayRegularizer:
    def __init__(self, regularization_coeff=0.0):
        self.coeff = regularization_coeff

    def append_regularization_op(self, param, grad):
        from .layers.nn import scale, sign
        from .layers.math_ops import elementwise_add
        decay = scale(sign(param), scale=self.coeff)
        return elementwise_add(grad, decay)


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer

"""Dygraph data parallelism (reference: dygraph/parallel.py DataParallel +
imperative/nccl_context.cc).

The reference allreduces coalesced grads over NCCL after backward. The TPU
equivalent: after loss.backward(), `apply_collective_grads` pmean-reduces
each param's grad across the mesh's dp axis. In single-process SPMD this is
usually unnecessary (GSPMD handles it inside jit), so the eager fallback
averages over jax.device_count() only when a multi-device mesh is active.
"""
from __future__ import annotations

import jax

from .layers import Layer

__all__ = ["DataParallel", "prepare_context", "Env", "ParallelEnv"]


class Env:
    def __init__(self):
        self.nranks = jax.device_count()
        self.local_rank = jax.process_index()
        self.dev_id = 0
        self.trainer_endpoints = []
        self.current_endpoint = ""


ParallelEnv = Env


def prepare_context(strategy=None):
    return Env()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        n = jax.device_count()
        return loss * (1.0 / n) if n > 1 else loss

    def apply_collective_grads(self):
        # Single-controller SPMD: grads already global under jit/GSPMD.
        # Multi-host eager DP would psum here over the dp mesh axis.
        pass

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_dict(self, *a, **kw):
        return self._layers.set_dict(*a, **kw)

    load_dict = set_dict

"""dygraph.nn layers (reference: python/paddle/fluid/dygraph/nn.py — Conv2D,
Pool2D, FC, BatchNorm, Embedding, LayerNorm, ... 16 classes)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import VarBase, trace_op
from .layers import Layer
from ..initializer import Constant, Normal

__all__ = ["Conv2D", "Pool2D", "FC", "Linear", "BatchNorm", "Embedding",
           "LayerNorm", "Dropout", "GroupNorm", "PRelu", "Conv3D",
           "Conv2DTranspose", "Conv3DTranspose", "GRUUnit", "NCE",
           "BilinearTensorProduct", "SpectralNorm", "TreeConv"]


class Conv2D(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, stride=1, padding=0, dilation=1,
                 groups=None, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        self._stride = [stride] * 2 if isinstance(stride, int) else stride
        self._padding = [padding] * 2 if isinstance(padding, int) \
            else padding
        self._dilation = [dilation] * 2 if isinstance(dilation, int) \
            else dilation
        self._act = act
        if isinstance(filter_size, int):
            filter_size = [filter_size] * 2
        fan = int(np.prod(filter_size)) * num_channels
        std = (2.0 / fan) ** 0.5
        self.weight = self.create_parameter(
            [num_filters, num_channels // self._groups] + list(filter_size),
            dtype, initializer=Normal(0.0, std))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_filters], dtype,
                                           is_bias=True))

    def forward(self, x):
        out = trace_op("conv2d", {"Input": [x], "Filter": [self.weight]},
                       {"strides": self._stride, "paddings": self._padding,
                        "dilations": self._dilation,
                        "groups": self._groups})["Output"][0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]},
                           {"axis": 1})["Out"][0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})["Out"][0]
        return out


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=-1, pool_type="max",
                 pool_stride=1, pool_padding=0, global_pooling=False,
                 use_cudnn=True, ceil_mode=False, exclusive=True):
        super().__init__(name_scope)
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if isinstance(pool_size, int)
            else pool_size,
            "strides": [pool_stride] * 2 if isinstance(pool_stride, int)
            else pool_stride,
            "paddings": [pool_padding] * 2 if isinstance(pool_padding, int)
            else pool_padding,
            "global_pooling": global_pooling, "ceil_mode": ceil_mode,
            "exclusive": exclusive}

    def forward(self, x):
        return trace_op("pool2d", {"X": [x]}, self._attrs)["Out"][0]


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(None, dtype)
        self._act = act
        self.weight = self.create_parameter([input_dim, output_dim], dtype)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([output_dim], dtype,
                                           is_bias=True))

    def forward(self, x):
        out = trace_op("mul", {"X": [x], "Y": [self.weight]},
                       {"x_num_col_dims": len(x.shape) - 1,
                        "y_num_col_dims": 1})["Out"][0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]},
                           {"axis": -1})["Out"][0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})["Out"][0]
        return out


class FC(Layer):
    """reference dygraph FC: flattens input to 2-D (num_flatten_dims)."""

    def __init__(self, name_scope=None, size=None, num_flatten_dims=1,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._nfd = num_flatten_dims
        self._act = act
        self._dtype = dtype
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, x):
        if self.weight is None:
            in_dim = int(np.prod(x.shape[self._nfd:]))
            self.weight = self.create_parameter([in_dim, self._size],
                                                self._dtype)
            self.add_parameter("weight", self.weight)
            if self._bias_attr is not False:
                self.bias = self.create_parameter([self._size], self._dtype,
                                                  is_bias=True)
                self.add_parameter("bias", self.bias)
        out = trace_op("mul", {"X": [x], "Y": [self.weight]},
                       {"x_num_col_dims": self._nfd,
                        "y_num_col_dims": 1})["Out"][0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]},
                           {"axis": self._nfd})["Out"][0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})["Out"][0]
        return out


class BatchNorm(Layer):
    def __init__(self, name_scope=None, num_channels=None, act=None,
                 is_test=False, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", use_global_stats=False):
        super().__init__(name_scope, dtype)
        c = num_channels
        self.weight = self.create_parameter([c], dtype,
                                            initializer=Constant(1.0))
        self.bias = self.create_parameter([c], dtype, is_bias=True)
        self._mean = VarBase(jnp.zeros(c), stop_gradient=True,
                             persistable=True, trainable=False)
        self._variance = VarBase(jnp.ones(c), stop_gradient=True,
                                 persistable=True, trainable=False)
        self._attrs = {"momentum": momentum, "epsilon": epsilon,
                       "data_layout": data_layout,
                       "use_global_stats": use_global_stats}
        self._act = act

    def forward(self, x):
        attrs = dict(self._attrs, is_test=not self.training)
        outs = trace_op("batch_norm",
                        {"X": [x], "Scale": [self.weight],
                         "Bias": [self.bias], "Mean": [self._mean],
                         "Variance": [self._variance]}, attrs)
        self._mean.value = outs["MeanOut"][0].value
        self._variance.value = outs["VarianceOut"][0].value
        y = outs["Y"][0]
        if self._act:
            y = trace_op(self._act, {"X": [y]}, {})["Out"][0]
        return y


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._padding_idx = -1 if padding_idx is None else padding_idx
        self.weight = self.create_parameter(size, dtype,
                                            initializer=Normal(0.0, 0.02))

    def forward(self, ids):
        op = "lookup_table" if ids.shape and ids.shape[-1] == 1 \
            else "lookup_table_v2"
        return trace_op(op, {"W": [self.weight], "Ids": [ids]},
                        {"padding_idx": self._padding_idx})["Out"][0]


class LayerNorm(Layer):
    def __init__(self, name_scope=None, normalized_shape=None, scale=True,
                 shift=True, epsilon=1e-5, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        n = int(np.prod(normalized_shape)) if \
            isinstance(normalized_shape, (list, tuple)) else normalized_shape
        self._eps = epsilon
        self._act = act
        self.weight = self.create_parameter([n], dtype,
                                            initializer=Constant(1.0)) \
            if scale else None
        self.bias = self.create_parameter([n], dtype, is_bias=True) \
            if shift else None

    def forward(self, x):
        ins = {"X": [x]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        y = trace_op("layer_norm", ins,
                     {"begin_norm_axis": len(x.shape) - 1,
                      "epsilon": self._eps})["Y"][0]
        if self._act:
            y = trace_op(self._act, {"X": [y]}, {})["Out"][0]
        return y


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer"):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, x):
        return trace_op("dropout", {"X": [x]},
                        {"dropout_prob": self._p,
                         "is_test": not self.training,
                         "dropout_implementation": self._impl})["Out"][0]


class GroupNorm(Layer):
    def __init__(self, name_scope=None, channels=None, groups=1,
                 epsilon=1e-5, dtype="float32", act=None):
        super().__init__(name_scope, dtype)
        self._groups = groups
        self._eps = epsilon
        self._act = act
        self.weight = self.create_parameter([channels], dtype,
                                            initializer=Constant(1.0))
        self.bias = self.create_parameter([channels], dtype, is_bias=True)

    def forward(self, x):
        y = trace_op("group_norm",
                     {"X": [x], "Scale": [self.weight],
                      "Bias": [self.bias]},
                     {"groups": self._groups, "epsilon": self._eps})["Y"][0]
        if self._act:
            y = trace_op(self._act, {"X": [y]}, {})["Out"][0]
        return y


class PRelu(Layer):
    def __init__(self, name_scope=None, mode="all", channel=None,
                 input_shape=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._mode = mode
        shape = {"all": [1], "channel": [channel]}.get(
            mode, list(input_shape or [1]))
        self.weight = self.create_parameter(shape, dtype,
                                            initializer=Constant(0.25))

    def forward(self, x):
        return trace_op("prelu", {"X": [x], "Alpha": [self.weight]},
                        {"mode": self._mode})["Out"][0]


class Conv3D(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, stride=1, padding=0, dilation=1,
                 groups=None, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        def _3(v):
            return [v] * 3 if isinstance(v, int) else list(v)
        self._stride = _3(stride)
        self._padding = _3(padding)
        self._dilation = _3(dilation)
        self._act = act
        fs = _3(filter_size)
        fan = int(np.prod(fs)) * num_channels
        self.weight = self.create_parameter(
            [num_filters, num_channels // self._groups] + fs, dtype,
            initializer=Normal(0.0, (2.0 / fan) ** 0.5))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_filters], dtype,
                                           is_bias=True))

    def forward(self, x):
        out = trace_op("conv3d", {"Input": [x], "Filter": [self.weight]},
                       {"strides": self._stride, "paddings": self._padding,
                        "dilations": self._dilation,
                        "groups": self._groups})["Output"][0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]},
                           {"axis": 1})["Out"][0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})["Out"][0]
        return out


class Conv2DTranspose(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, output_size=None, padding=0, stride=1,
                 dilation=1, groups=None, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        def _2(v):
            return [v] * 2 if isinstance(v, int) else list(v)
        self._stride = _2(stride)
        self._padding = _2(padding)
        self._dilation = _2(dilation)
        self._output_size = output_size
        self._act = act
        fs = _2(filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // self._groups] + fs, dtype,
            initializer=Normal(0.0, 0.02))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_filters], dtype,
                                           is_bias=True))

    def forward(self, x):
        attrs = {"strides": self._stride, "paddings": self._padding,
                 "dilations": self._dilation, "groups": self._groups}
        if self._output_size is not None:
            fs = self.weight.shape[-2:]
            natural = [(int(x.shape[2 + i]) - 1) * self._stride[i]
                       - 2 * self._padding[i]
                       + self._dilation[i] * (fs[i] - 1) + 1
                       for i in range(2)]
            want = list(self._output_size)
            extra = [want[i] - natural[i] for i in range(2)]
            # reference conv2d_transpose accepts the whole reachable
            # range [natural, natural + stride); realized by trimming
            # less off the bottom/right of the col2im buffer
            if any(e < 0 or e >= self._stride[i]
                   for i, e in enumerate(extra)):
                raise ValueError(
                    f"Conv2DTranspose: output_size {want} unreachable "
                    f"with stride/padding/filter (natural output "
                    f"{natural}, reachable up to "
                    f"{[natural[i] + self._stride[i] - 1 for i in range(2)]})")
            if any(extra):
                attrs["output_padding"] = extra
        out = trace_op("conv2d_transpose",
                       {"Input": [x], "Filter": [self.weight]},
                       attrs)["Output"][0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]},
                           {"axis": 1})["Out"][0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})["Out"][0]
        return out


class Conv3DTranspose(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, output_size=None, padding=0, stride=1,
                 dilation=1, groups=None, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        def _3(v):
            return [v] * 3 if isinstance(v, int) else list(v)
        self._stride = _3(stride)
        self._padding = _3(padding)
        self._dilation = _3(dilation)
        self._output_size = output_size
        self._act = act
        fs = _3(filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // self._groups] + fs, dtype,
            initializer=Normal(0.0, 0.02))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_filters], dtype,
                                           is_bias=True))

    def forward(self, x):
        attrs = {"strides": self._stride, "paddings": self._padding,
                 "dilations": self._dilation, "groups": self._groups}
        if self._output_size is not None:
            fs = self.weight.shape[-3:]
            natural = [(int(x.shape[2 + i]) - 1) * self._stride[i]
                       - 2 * self._padding[i]
                       + self._dilation[i] * (fs[i] - 1) + 1
                       for i in range(3)]
            want = list(self._output_size)
            extra = [want[i] - natural[i] for i in range(3)]
            # reachable range [natural, natural + stride), as in the
            # reference conv3d_transpose
            if any(e < 0 or e >= self._stride[i]
                   for i, e in enumerate(extra)):
                raise ValueError(
                    f"Conv3DTranspose: output_size {want} unreachable "
                    f"with stride/padding/filter (natural output "
                    f"{natural}, reachable up to "
                    f"{[natural[i] + self._stride[i] - 1 for i in range(3)]})")
            if any(extra):
                attrs["output_padding"] = extra
        out = trace_op("conv3d_transpose",
                       {"Input": [x], "Filter": [self.weight]},
                       attrs)["Output"][0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]},
                           {"axis": 1})["Out"][0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})["Out"][0]
        return out


class GRUUnit(Layer):
    def __init__(self, name_scope=None, size=None, param_attr=None,
                 bias_attr=None, activation="tanh",
                 gate_activation="sigmoid", origin_mode=False,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        d = size // 3
        self._attrs = {"activation": activation,
                       "gate_activation": gate_activation,
                       "origin_mode": origin_mode}
        self.weight = self.create_parameter([d, d * 3], dtype)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([1, d * 3], dtype,
                                           is_bias=True))

    def forward(self, input, hidden):
        ins = {"Input": [input], "HiddenPrev": [hidden],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = trace_op("gru_unit", ins, self._attrs)
        return (out["Hidden"][0], out["ResetHiddenPrev"][0],
                out["Gate"][0])


class NCE(Layer):
    def __init__(self, name_scope=None, num_total_classes=None, dim=None,
                 sample_weight=None, param_attr=None, bias_attr=None,
                 num_neg_samples=None, sampler="uniform", custom_dist=None,
                 seed=0, is_sparse=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"num_total_classes": num_total_classes,
                       "num_neg_samples": num_neg_samples or 10,
                       "seed": seed}
        self.weight = self.create_parameter([num_total_classes, dim],
                                            dtype)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([num_total_classes], dtype,
                                           is_bias=True))

    def forward(self, input, label, sample_weight=None):
        ins = {"Input": [input], "Label": [label],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        return trace_op("nce", ins, self._attrs)["Cost"][0]


class BilinearTensorProduct(Layer):
    def __init__(self, name_scope=None, size=None, x_dim=None, y_dim=None,
                 name=None, act=None, param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self.weight = self.create_parameter([size, x_dim, y_dim], dtype)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([1, size], dtype, is_bias=True))

    def forward(self, x, y):
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = trace_op("bilinear_tensor_product", ins, {})["Out"][0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})["Out"][0]
        return out


class SpectralNorm(Layer):
    def __init__(self, name_scope=None, weight_shape=None, dim=0,
                 power_iters=1, eps=1e-12, name=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"dim": dim, "power_iters": power_iters, "eps": eps}
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], dtype, initializer=Normal(0.0, 1.0))
        self.weight_v = self.create_parameter(
            [w], dtype, initializer=Normal(0.0, 1.0))

    def forward(self, weight):
        return trace_op("spectral_norm",
                        {"Weight": [weight], "U": [self.weight_u],
                         "V": [self.weight_v]},
                        self._attrs)["Out"][0]


class TreeConv(Layer):
    def __init__(self, name_scope=None, output_size=None, num_filters=1,
                 max_depth=8, act="tanh", param_attr=None, bias_attr=None,
                 name=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self._feature_size = None
        self._output_size = output_size
        self._num_filters = num_filters
        self._max_depth = max_depth
        self.weight = None
        self.bias = None
        self._bias_attr = bias_attr

    def forward(self, nodes_vector, edge_set):
        if self.weight is None:
            feature = int(nodes_vector.shape[-1])
            self.weight = self.create_parameter(
                [feature, 3, self._output_size, self._num_filters],
                self._dtype)
            if self._bias_attr is not False:
                self.bias = self.create_parameter(
                    [self._num_filters], self._dtype, is_bias=True)
        out = trace_op("tree_conv",
                       {"NodesVector": [nodes_vector],
                        "EdgeSet": [edge_set], "Filter": [self.weight]},
                       {"max_depth": self._max_depth})["Out"][0]
        if self.bias is not None:
            out = trace_op("elementwise_add",
                           {"X": [out], "Y": [self.bias]},
                           {"axis": -1})["Out"][0]
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})["Out"][0]
        return out

"""dygraph.Layer base class (reference: dygraph/layers.py:33)."""
from __future__ import annotations

from typing import Dict, Iterator, Tuple

import numpy as np

from . import VarBase

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._parameters: Dict[str, VarBase] = {}
        self._sub_layers: Dict[str, "Layer"] = {}
        self._full_name = name_scope or type(self).__name__.lower()
        self._dtype = dtype
        self.training = True

    def full_name(self):
        return self._full_name

    # -- parameter management -------------------------------------------
    def create_parameter(self, shape, dtype="float32", initializer=None,
                         is_bias=False, attr=None):
        import jax
        import jax.numpy as jnp
        from ..core.dtypes import as_np_dtype
        from ..initializer import Constant, Xavier
        init = initializer or (attr.initializer if attr is not None and
                               getattr(attr, "initializer", None) else None)
        shape = [int(s) for s in shape]
        key = jax.random.PRNGKey(np.random.randint(0, 2 ** 31))
        npdtype = as_np_dtype(dtype)
        if init is None:
            init = Constant(0.0) if is_bias else Xavier()
        value = _materialise_init(init, shape, npdtype, key)
        p = VarBase(jnp.asarray(value), persistable=True,
                    stop_gradient=False)
        return p

    def add_parameter(self, name, param):
        self._parameters[name] = param
        return param

    def add_sublayer(self, name, layer):
        self._sub_layers[name] = layer
        return layer

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for sub in self._sub_layers.values():
                out.extend(sub.parameters())
        return out

    def named_parameters(self, prefix="") -> Iterator[Tuple[str, VarBase]]:
        for n, p in self._parameters.items():
            yield (f"{prefix}.{n}" if prefix else n), p
        for sn, sub in self._sub_layers.items():
            yield from sub.named_parameters(
                f"{prefix}.{sn}" if prefix else sn)

    def sublayers(self, include_sublayers=True):
        out = list(self._sub_layers.values())
        if include_sublayers:
            for s in self._sub_layers.values():
                out.extend(s.sublayers())
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- train/eval ------------------------------------------------------
    def train(self):
        from . import _state
        _state["is_test"] = False
        self.training = True
        for s in self.sublayers():
            s.training = True

    def eval(self):
        from . import _state
        _state["is_test"] = True
        self.training = False
        for s in self.sublayers():
            s.training = False

    # -- state dict ------------------------------------------------------
    def state_dict(self, include_sublayers=True):
        return {n: p.numpy() for n, p in self.named_parameters()}

    def set_dict(self, state, include_sublayers=True):
        import jax.numpy as jnp
        for n, p in self.named_parameters():
            if n in state:
                p.value = jnp.asarray(state[n])

    load_dict = set_dict

    # -- call ------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __setattr__(self, name, value):
        if isinstance(value, VarBase) and value.persistable:
            self.__dict__.setdefault("_parameters", {})[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault("_sub_layers", {})[name] = value
        object.__setattr__(self, name, value)


def _materialise_init(init, shape, dtype, key):
    """Run an initializer spec eagerly (reference initializers emit startup
    ops; eager mode materialises directly)."""
    import jax
    import math
    import numpy as np
    from .. import initializer as I
    if isinstance(init, I.ConstantInitializer):
        return np.full(shape, init.value, dtype)
    if isinstance(init, I.UniformInitializer):
        return np.asarray(jax.random.uniform(
            key, shape, minval=init.low, maxval=init.high)).astype(dtype)
    if isinstance(init, I.NormalInitializer):
        return np.asarray(jax.random.normal(key, shape) * init.scale +
                          init.loc).astype(dtype)
    if isinstance(init, I.TruncatedNormalInitializer):
        return np.asarray(jax.random.truncated_normal(
            key, -2.0, 2.0, shape) * init.scale + init.loc).astype(dtype)
    if isinstance(init, I.XavierInitializer):
        fin, fout = I._fans(_Shaped(shape))
        fin = init.fan_in if init.fan_in is not None else fin
        fout = init.fan_out if init.fan_out is not None else fout
        if init.uniform:
            lim = math.sqrt(6.0 / (fin + fout))
            return np.asarray(jax.random.uniform(
                key, shape, minval=-lim, maxval=lim)).astype(dtype)
        std = math.sqrt(2.0 / (fin + fout))
        return np.asarray(jax.random.normal(key, shape) * std).astype(dtype)
    if isinstance(init, I.MSRAInitializer):
        fin, _ = I._fans(_Shaped(shape))
        fin = init.fan_in if init.fan_in is not None else fin
        if init.uniform:
            lim = math.sqrt(6.0 / fin)
            return np.asarray(jax.random.uniform(
                key, shape, minval=-lim, maxval=lim)).astype(dtype)
        return np.asarray(jax.random.normal(key, shape) *
                          math.sqrt(2.0 / fin)).astype(dtype)
    if isinstance(init, I.NumpyArrayInitializer):
        return np.asarray(init.value, dtype).reshape(shape)
    raise TypeError(f"unsupported initializer {init!r} in dygraph")


class _Shaped:
    def __init__(self, shape):
        self.shape = tuple(shape)

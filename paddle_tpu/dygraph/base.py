"""Dygraph functional helpers (reference dygraph/base.py)."""
from __future__ import annotations

from . import VarBase, _run_backward, _state

__all__ = ["grad"]


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad-style double-grad entry: re-runs tape backward and
    collects input grads without mutating .grad on leaves."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = {id(p): p.grad for p in inputs}
    for p in inputs:
        p.grad = None
    _run_backward(outputs[0])
    out = [p.grad for p in inputs]
    for p in inputs:
        p.grad = saved[id(p)]
    return out

"""Dygraph→static capture: the deploy bridge from eager mode.

Reference: python/paddle/fluid/dygraph/jit.py:46 `TracedLayer.trace` over
imperative/jit/ProgramDescTracer (program_desc_tracer.h:32) — re-runs of
the traced layer go through an Executor on the captured ProgramDesc, and
`save_inference_model` exports it for serving.

Here the dygraph tape already records every executed op with stable var
identities (dygraph.trace_op), so capture = replay the tape slice into a
Program: parameters become persistable vars (values snapshotted into the
TracedLayer's scope), leaf inputs become feeds.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

__all__ = ["TracedLayer", "trace"]


class TracedLayer:
    def __init__(self, program, feed_names, fetch_names, param_values):
        from ..core.scope import Scope
        from ..executor import Executor

        self.program = program
        self._feed_names = feed_names
        self._fetch_names = fetch_names
        self._scope = Scope()
        for n, v in param_values.items():
            self._scope.set(n, v)
        self._exe = Executor()

    @staticmethod
    def trace(layer, inputs):
        """Returns (outputs, traced_layer) — reference jit.py TracedLayer
        API. Must run inside dygraph.guard()."""
        from . import VarBase, _state

        if not _state["enabled"] or _state["tape"] is None:
            raise RuntimeError("TracedLayer.trace must run inside "
                               "dygraph.guard() with gradients enabled")
        inputs = list(inputs)
        tape = _state["tape"]
        start = len(tape)
        outputs = layer(*inputs)
        out_list = outputs if isinstance(outputs, (list, tuple)) \
            else [outputs]
        entries = tape[start:]
        program, feed_names, fetch_names, params = _capture(
            entries, inputs, out_list)
        return outputs, TracedLayer(program, feed_names, fetch_names,
                                    params)

    def __call__(self, inputs):
        feed = {n: (v.numpy() if hasattr(v, "numpy") else np.asarray(v))
                for n, v in zip(self._feed_names, inputs)}
        return self._exe.run(self.program, feed=feed,
                             fetch_list=self._fetch_names,
                             scope=self._scope)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from .. import io as fio
        from ..core.scope import scope_guard

        with scope_guard(self._scope):
            fio.save_inference_model(
                dirname, self._feed_names,
                [self.program.global_block().var(n)
                 for n in self._fetch_names],
                self._exe, main_program=self.program)


def _capture(entries, inputs, outputs):
    """Tape slice -> Program. Vars keep their eager names; anything read
    before being produced is either a traced input (feed) or a parameter
    (persistable, value snapshotted)."""
    from ..framework import Program

    program = Program()
    block = program.global_block()
    produced = set()
    params: Dict[str, np.ndarray] = {}
    input_names = {v.name for v in inputs}

    def ensure_var(v, persistable=False):
        if not block.has_var(v.name):
            block.create_var(name=v.name, shape=tuple(v.shape),
                             dtype=v.dtype, persistable=persistable,
                             stop_gradient=True)

    for v in inputs:
        ensure_var(v)

    for e in entries:
        for slot, vs in e.ins.items():
            for v in vs:
                if v.name in produced or v.name in input_names:
                    ensure_var(v)
                    continue
                # read-before-write: a captured constant/parameter
                ensure_var(v, persistable=True)
                params.setdefault(v.name, np.asarray(v.value))
        for slot, vs in e.outs.items():
            for v in vs:
                ensure_var(v)
                produced.add(v.name)
        block.append_op(
            e.op_type,
            inputs={s: [v.name for v in vs] for s, vs in e.ins.items()},
            outputs={s: [v.name for v in vs] for s, vs in e.outs.items()},
            attrs=dict(e.attrs), infer_shape=False)

    feed_names = [v.name for v in inputs]
    fetch_names = [v.name for v in outputs]
    return program, feed_names, fetch_names, params


def trace(layer, inputs):
    """Module-level alias (reference dygraph.jit.trace)."""
    return TracedLayer.trace(layer, inputs)

"""Dygraph learning-rate decay objects (reference:
python/paddle/fluid/dygraph/learning_rate_scheduler.py — LearningRateDecay
base + PiecewiseDecay/NaturalExpDecay/ExponentialDecay/InverseTimeDecay/
PolynomialDecay/CosineDecay/NoamDecay).

Pass an instance as `learning_rate=` to any optimizer; each
optimizer.minimize() in dygraph mode advances the schedule one step and
uses the returned float. Pure host math — the eager update consumes a
scalar, no LR var lives in a Program."""
from __future__ import annotations

import math

__all__ = ["LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
           "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
           "CosineDecay", "NoamDecay"]


class LearningRateDecay:
    def __init__(self, begin=0, step=1):
        self.step_num = begin
        self.step_size = step

    def step(self) -> float:
        """Return the current LR, then advance one schedule step."""
        lr = self()
        self.step_num += self.step_size
        return lr

    def __call__(self) -> float:
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1):
        super().__init__(begin, step)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def __call__(self):
        for b, v in zip(self.boundaries, self.values):
            if self.step_num < b:
                return float(v)
        return float(self.values[len(self.boundaries)])


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr0, self.ds, self.dr = learning_rate, decay_steps, decay_rate
        self.staircase = staircase

    def __call__(self):
        t = self.step_num / self.ds
        if self.staircase:
            t = math.floor(t)
        return float(self.lr0 * math.exp(-self.dr * t))


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr0, self.ds, self.dr = learning_rate, decay_steps, decay_rate
        self.staircase = staircase

    def __call__(self):
        t = self.step_num / self.ds
        if self.staircase:
            t = math.floor(t)
        return float(self.lr0 * self.dr ** t)


class InverseTimeDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr0, self.ds, self.dr = learning_rate, decay_steps, decay_rate
        self.staircase = staircase

    def __call__(self):
        t = self.step_num / self.ds
        if self.staircase:
            t = math.floor(t)
        return float(self.lr0 / (1.0 + self.dr * t))


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr0 = learning_rate
        self.ds = decay_steps
        self.end_lr = end_learning_rate
        self.power = power
        self.cycle = cycle

    def __call__(self):
        step = self.step_num
        ds = self.ds
        if self.cycle:
            mult = max(1.0, math.ceil(step / ds) or 1.0)
            ds = ds * mult
        else:
            step = min(step, ds)
        frac = (1.0 - step / ds) ** self.power
        return float((self.lr0 - self.end_lr) * frac + self.end_lr)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1):
        super().__init__(begin, step)
        self.lr0 = learning_rate
        self.spe = step_each_epoch
        self.epochs = epochs

    def __call__(self):
        epoch = self.step_num // self.spe
        return float(self.lr0 * 0.5 *
                     (math.cos(epoch * math.pi / self.epochs) + 1.0))


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1):
        super().__init__(begin, step)
        self.d_model = d_model
        self.warmup = warmup_steps

    def __call__(self):
        n = max(self.step_num, 1)
        return float(self.d_model ** -0.5 *
                     min(n ** -0.5, n * self.warmup ** -1.5))

"""Dygraph save/load (reference dygraph/checkpoint.py)."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path):
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    np.savez(model_path + ".pdparams",
             **{k: np.asarray(v) for k, v in state_dict.items()})


def load_dygraph(model_path):
    blob = np.load(model_path + ".pdparams")
    return {k: blob[k] for k in blob.files}, None

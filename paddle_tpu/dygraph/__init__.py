"""Dygraph: eager execution over the same op registry.

Reference: paddle/fluid/imperative/ — Tracer::TraceOp runs the kernel
immediately and records OpBase grad nodes (tracer.cc:45,86); BasicEngine
walks them on backward() (engine.h:69). Here TraceOp runs the op's JAX
lowering eagerly (jax is itself an eager-dispatch runtime on TPU) and
records a tape entry; backward() replays the tape in reverse through the
same generic-vjp machinery the static graph uses (core/lowering.py) — one
autograd implementation for both modes.
"""
from __future__ import annotations

import contextlib
import weakref
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import as_np_dtype, is_floating
from ..core.registry import REGISTRY

__all__ = ["guard", "enabled", "to_variable", "VarBase", "trace_op",
           "Layer", "no_grad", "save_dygraph", "load_dygraph"]

_state = {"enabled": False, "tape": None, "op_counter": 0, "seed": 0,
          "is_test": False, "var_map": None}


def enabled():
    return _state["enabled"]


@contextlib.contextmanager
def guard(place=None):
    old = dict(_state)
    # WeakValueDictionary: name lookup for layers.* dispatch must not pin
    # temp outputs — vars die with their last real reference, matching the
    # reference dygraph's refcount-driven frees.
    _state.update(enabled=True, tape=[], op_counter=0,
                  var_map=weakref.WeakValueDictionary())
    try:
        yield
    finally:
        _state.update(old)


@contextlib.contextmanager
def no_grad():
    old_tape = _state["tape"]
    _state["tape"] = None
    try:
        yield
    finally:
        _state["tape"] = old_tape


class VarBase:
    """Eager tensor + autograd leaf (imperative/layer.h:55)."""

    _counter = [0]

    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False, trainable=True):
        # value=None creates an unbound placeholder (filled by the layer
        # dispatch in LayerHelper.append_op before anyone reads it)
        if value is None:
            self.value = None
        else:
            self.value = value if isinstance(value, jax.Array) else \
                jnp.asarray(value)
        VarBase._counter[0] += 1
        self.name = name or f"eager_{VarBase._counter[0]}"
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self.grad: Optional[jax.Array] = None
        # name→var registry so name-keyed layers.* calls resolve eager vars
        # (the reference's dygraph scope; imperative/layer.h VarBase names)
        vm = _state.get("var_map")
        if _state["enabled"] and vm is not None:
            vm[self.name] = self

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return jnp.dtype(self.value.dtype).name

    def numpy(self):
        return np.asarray(self.value)

    def set_value(self, v):
        self.value = jnp.asarray(v)

    def clear_gradient(self):
        self.grad = None

    def gradient(self):
        return None if self.grad is None else np.asarray(self.grad)

    def detach(self):
        return VarBase(self.value, stop_gradient=True)

    def astype(self, dtype):
        return trace_op("cast", {"X": [self]},
                        {"out_dtype": str(dtype)})["Out"][0]

    def backward(self):
        _run_backward(self)

    # operator sugar
    def _bin(self, other, op):
        if not isinstance(other, VarBase):
            other = VarBase(jnp.asarray(other, self.value.dtype),
                            stop_gradient=True)
        return trace_op(op, {"X": [self], "Y": [other]}, {})["Out"][0]

    def __add__(self, o):
        return self._bin(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._bin(o, "elementwise_sub")

    def __mul__(self, o):
        return self._bin(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin(o, "elementwise_div")

    def __rsub__(self, o):
        return to_variable(jnp.asarray(o, self.value.dtype))._bin(
            self, "elementwise_sub")

    def __rtruediv__(self, o):
        return to_variable(jnp.asarray(o, self.value.dtype))._bin(
            self, "elementwise_div")

    def __pow__(self, o):
        return trace_op("pow", {"X": [self]}, {"factor": float(o)})["Out"][0]

    def __neg__(self):
        return trace_op("scale", {"X": [self]},
                        {"scale": -1.0, "bias": 0.0})["Out"][0]

    def __matmul__(self, o):
        return self._bin(o, "matmul")

    def _reduce(self, op_type, dim=None, keep_dim=False):
        attrs = {"dim": list(dim) if dim is not None else None,
                 "keep_dim": keep_dim,
                 "reduce_all": dim is None}
        return trace_op(op_type, {"X": [self]}, attrs)["Out"][0]

    def mean(self, dim=None, keep_dim=False):
        return self._reduce("reduce_mean", dim, keep_dim)

    def sum(self, dim=None, keep_dim=False):
        return self._reduce("reduce_sum", dim, keep_dim)

    def max(self, dim=None, keep_dim=False):
        return self._reduce("reduce_max", dim, keep_dim)

    def min(self, dim=None, keep_dim=False):
        return self._reduce("reduce_min", dim, keep_dim)

    def reshape(self, shape):
        return trace_op("reshape2", {"X": [self]},
                        {"shape": list(shape)})["Out"][0]

    def transpose(self, perm):
        return trace_op("transpose2", {"X": [self]},
                        {"axis": list(perm)})["Out"][0]

    def __repr__(self):
        return f"VarBase({self.name}, shape={self.shape})\n{self.numpy()}"


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    return VarBase(jnp.asarray(value), name=name, stop_gradient=True)


class _EagerCtx:
    def __init__(self, op_id):
        self.is_test = _state["is_test"]
        self.mesh = None
        key = jax.random.PRNGKey(_state["seed"])
        self._key = jax.random.fold_in(key, np.uint32(op_id))

    @property
    def rng(self):
        return self._key


class _TapeEntry:
    __slots__ = ("op_type", "attrs", "ins", "outs", "op_id")

    def __init__(self, op_type, attrs, ins, outs, op_id):
        self.op_type = op_type
        self.attrs = attrs
        self.ins = ins      # {slot: [VarBase]}
        self.outs = outs    # {slot: [VarBase]}
        self.op_id = op_id


def trace_op(op_type, ins: Dict[str, List[VarBase]], attrs,
             out_vars: Optional[Dict[str, List[VarBase]]] = None) -> Dict[
        str, List[VarBase]]:
    """Run one op eagerly; record on the tape (tracer.cc:45 TraceOp).
    out_vars: pre-created placeholders to bind results into (keeps tape
    identity when layers.* pre-allocates its output vars)."""
    opdef = REGISTRY.get(op_type)
    _state["op_counter"] += 1
    op_id = _state["op_counter"]
    ctx = _EagerCtx(op_id)
    arr_ins = {s: [v.value for v in vs] for s, vs in ins.items() if vs}
    arr_outs = opdef.lower(ctx, arr_ins, attrs)
    if out_vars is not None:
        outs = {}
        for s, arrs in arr_outs.items():
            slots = out_vars.get(s, [])
            bound = []
            for i, a in enumerate(arrs):
                if i < len(slots):
                    slots[i].value = a
                    bound.append(slots[i])
                else:
                    bound.append(VarBase(a))
            outs[s] = bound
    else:
        outs = {s: [VarBase(a) for a in arrs]
                for s, arrs in arr_outs.items()}
    tape = _state["tape"]
    needs_grad = any(not v.stop_gradient for vs in ins.values() for v in vs)
    if tape is not None and needs_grad and not opdef.inplace:
        tape.append(_TapeEntry(op_type, dict(attrs), ins, outs, op_id))
    else:
        for vs in outs.values():
            for v in vs:
                v.stop_gradient = True
    return outs


def _run_backward(loss: VarBase):
    """BasicEngine::Execute (engine.h:69): reverse-tape vjp replay with
    gradient accumulation (gradient_accumulator.cc)."""
    tape = _state["tape"]
    if tape is None:
        raise RuntimeError("backward() outside dygraph guard")
    grads: Dict[int, jax.Array] = {
        id(loss): jnp.ones(loss.shape, loss.value.dtype)}
    var_of: Dict[int, VarBase] = {id(loss): loss}

    for entry in reversed(tape):
        opdef = REGISTRY.get(entry.op_type)
        out_cots = {}
        any_grad = False
        for slot, vs in entry.outs.items():
            if slot in opdef.nondiff_outputs:
                continue
            cots = []
            for v in vs:
                g = grads.get(id(v))
                any_grad = any_grad or g is not None
                cots.append(g)
            out_cots[slot] = cots
        if not any_grad:
            continue

        ctx = _EagerCtx(entry.op_id)
        arr_ins = {s: [v.value for v in vs]
                   for s, vs in entry.ins.items() if vs}
        diff_slots = [
            s for s, vs in entry.ins.items()
            if s not in opdef.nondiff_inputs
            and all(is_floating(v.value.dtype) for v in vs)
            and any(not v.stop_gradient for v in vs)]
        if not diff_slots:
            continue
        nondiff = {s: arr_ins[s] for s in arr_ins if s not in diff_slots}

        def f(diff):
            full = dict(nondiff)
            full.update(diff)
            outs = opdef.lower(ctx, full, entry.attrs)
            return {s: outs[s] for s in out_cots if s in outs}

        diff_in = {s: arr_ins[s] for s in diff_slots}
        primal, vjp = jax.vjp(f, diff_in)
        cots = {}
        for slot, prim in primal.items():
            given = out_cots.get(slot, [None] * len(prim))
            cots[slot] = [g if g is not None else jnp.zeros(p.shape, p.dtype)
                          for g, p in zip(given, prim)]
        (gin,) = vjp(cots)
        for slot, garrs in gin.items():
            for v, g in zip(entry.ins[slot], garrs):
                if v.stop_gradient:
                    continue
                prev = grads.get(id(v))
                grads[id(v)] = g if prev is None else prev + g
                var_of[id(v)] = v

    for vid, g in grads.items():
        v = var_of[vid]
        if v.trainable and not v.stop_gradient:
            v.grad = g if v.grad is None else v.grad + g


from .layers import Layer  # noqa: E402,F401
from .checkpoint import save_dygraph, load_dygraph  # noqa: E402,F401
from .nn import (Conv2D, Pool2D, FC, Linear, BatchNorm, Embedding,  # noqa: E402,F401
                 LayerNorm, Dropout, GroupNorm, PRelu, Conv3D,
                 Conv2DTranspose, Conv3DTranspose, GRUUnit, NCE,
                 BilinearTensorProduct, SpectralNorm, TreeConv)
from .parallel import DataParallel, prepare_context  # noqa: E402,F401
from .base import grad  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from .jit import TracedLayer  # noqa: E402,F401
from .learning_rate_scheduler import (  # noqa: E402,F401
    LearningRateDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
    InverseTimeDecay, PolynomialDecay, CosineDecay, NoamDecay)

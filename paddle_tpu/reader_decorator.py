"""Functional data-pipeline combinators.

Reference: python/paddle/reader/decorator.py — readers are nullary
callables returning sample generators; decorators compose them (shuffle,
batch, buffered, map, chain, compose, firstn, cache, xmap_readers). These
feed DataFeeder/DataLoader; on TPU the batched output goes straight to
the host-infeed path.
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading
import time

from .monitor import STAT_ADD, STAT_OBSERVE, STAT_SET

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "firstn", "xmap_readers", "multiprocess_reader",
           "batch", "ComposeNotAligned", "ReaderWorkerDied"]


class ComposeNotAligned(ValueError):
    pass


class ReaderWorkerDied(RuntimeError):
    """A multiprocess_reader worker exited without finishing its stream
    (OOM-kill, SIGKILL, crash) — raised in the consumer instead of
    hanging forever on a queue that will never fill."""


def cache(reader):
    state = {"data": None}

    def r():
        if state["data"] is None:
            # materialize into a local first: a partial read that raises
            # must not leave a half-filled cache behind
            state["data"] = list(reader())
        return iter(state["data"])
    return r


def map_readers(func, *readers):
    def r():
        for vals in zip(*[rd() for rd in readers]):
            yield func(*vals)
    return r


def shuffle(reader, buf_size):
    def r():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return r


def chain(*readers):
    def r():
        return itertools.chain(*[rd() for rd in readers])
    return r


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    _end = object()

    def r():
        rs = [rd() for rd in readers]
        if check_alignment:
            # zip() would consume one extra element from longer readers
            # before noticing a short one; zip_longest sees the ragged
            # tail regardless of argument order
            for items in itertools.zip_longest(*rs, fillvalue=_end):
                if any(i is _end for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())
    return r


class _ReaderError:
    def __init__(self, exc):
        self.exc = exc


def buffered(reader, size):
    """Background-thread prefetch (the host half of the reference's
    double-buffered reader, operators/reader/buffered_reader.cc). A
    source-reader exception re-raises in the consumer, never a silently
    truncated stream."""
    end = object()

    def r():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for e in reader():
                    q.put(e)
                q.put(end)
            except BaseException as exc:  # propagate to consumer
                q.put(_ReaderError(exc))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            # same starvation signal as reader.DataLoader: time the
            # consumer spends blocked on the prefetch queue
            t0 = time.perf_counter()
            e = q.get()
            STAT_OBSERVE("reader.batch_wait_seconds",
                         time.perf_counter() - t0)
            STAT_SET("reader.queue_depth", q.qsize())
            if e is end:
                return
            if isinstance(e, _ReaderError):
                raise e.exc
            STAT_ADD("reader.batches")
            yield e
    return r


def firstn(reader, n):
    def r():
        return itertools.islice(reader(), n)
    return r


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Thread-pool map over a reader (reference uses threads too)."""
    end = object()

    def r():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            try:
                for i, e in enumerate(reader()):
                    in_q.put((i, e))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as exc:
                out_q.put(_ReaderError(exc))  # surface + unblock consumer

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, e = item
                try:
                    out_q.put((i, mapper(e)))
                except BaseException as exc:
                    out_q.put(_ReaderError(exc))
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, _ReaderError):
                raise item.exc
            i, v = item
            if not order:
                yield v
            else:
                pending[i] = v
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]
    return r


def _mp_worker(reader, q, idx):
    """Module-level so the spawn context can pickle it. Protocol:
    ("item", sample)* then ("end", idx); an exception sends
    ("error", idx, exc) instead of the end sentinel."""
    try:
        for e in reader():
            q.put(("item", e))
    except BaseException as exc:  # noqa: BLE001 — ship it to the consumer
        try:
            q.put(("error", idx, exc))
        except Exception:  # unpicklable exception: send its repr
            q.put(("error", idx, RuntimeError(repr(exc))))
        return
    q.put(("end", idx))


def multiprocess_reader(readers, use_pipe=True, queue_size=1000,
                        get_timeout_s=1.0):
    """Run each reader in its own OS process (spawn context — jax's
    runtime does not survive fork()), multiplexed onto one bounded
    queue. Samples interleave in arrival order (`use_pipe` is accepted
    for reference API compatibility; the transport is always a
    multiprocessing queue).

    Every queue read is bounded by ``get_timeout_s``; on timeout the
    consumer checks worker liveness and raises :class:`ReaderWorkerDied`
    naming the exit code when a worker vanished without its end
    sentinel — the alternative is a training loop blocked forever on a
    queue no one will ever fill."""
    import multiprocessing as mp
    readers = list(readers)
    if not readers:
        raise ValueError("multiprocess_reader: need at least one reader")

    def r():
        ctx = mp.get_context("spawn")
        q = ctx.Queue(queue_size)
        procs = [ctx.Process(target=_mp_worker, args=(rd, q, i),
                             daemon=True)
                 for i, rd in enumerate(readers)]
        for p in procs:
            p.start()
        live = set(range(len(procs)))
        try:
            while live:
                t0 = time.perf_counter()
                try:
                    msg = q.get(timeout=get_timeout_s)
                except queue.Empty:
                    for i in sorted(live):
                        p = procs[i]
                        if p.is_alive():
                            continue
                        if p.exitcode == 0:
                            # clean exit whose sentinel we somehow
                            # missed: treat the stream as finished
                            live.discard(i)
                            continue
                        STAT_ADD("reader.worker_deaths")
                        raise ReaderWorkerDied(
                            f"multiprocess_reader worker {i} died with "
                            f"exit code {p.exitcode} before finishing "
                            f"its stream")
                    continue
                STAT_OBSERVE("reader.batch_wait_seconds",
                             time.perf_counter() - t0)
                kind = msg[0]
                if kind == "end":
                    live.discard(msg[1])
                elif kind == "error":
                    raise msg[2]
                else:
                    STAT_ADD("reader.batches")
                    yield msg[1]
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=2.0)
            q.close()
    return r


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (python/paddle/batch.py)."""
    def r():
        b = []
        for e in reader():
            b.append(e)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return r

"""Functional data-pipeline combinators.

Reference: python/paddle/reader/decorator.py — readers are nullary
callables returning sample generators; decorators compose them (shuffle,
batch, buffered, map, chain, compose, firstn, cache, xmap_readers). These
feed DataFeeder/DataLoader; on TPU the batched output goes straight to
the host-infeed path.
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading
import time

from .monitor import STAT_ADD, STAT_OBSERVE, STAT_SET

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "firstn", "xmap_readers", "multiprocess_reader",
           "batch", "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    state = {"data": None}

    def r():
        if state["data"] is None:
            # materialize into a local first: a partial read that raises
            # must not leave a half-filled cache behind
            state["data"] = list(reader())
        return iter(state["data"])
    return r


def map_readers(func, *readers):
    def r():
        for vals in zip(*[rd() for rd in readers]):
            yield func(*vals)
    return r


def shuffle(reader, buf_size):
    def r():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return r


def chain(*readers):
    def r():
        return itertools.chain(*[rd() for rd in readers])
    return r


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    _end = object()

    def r():
        rs = [rd() for rd in readers]
        if check_alignment:
            # zip() would consume one extra element from longer readers
            # before noticing a short one; zip_longest sees the ragged
            # tail regardless of argument order
            for items in itertools.zip_longest(*rs, fillvalue=_end):
                if any(i is _end for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())
    return r


class _ReaderError:
    def __init__(self, exc):
        self.exc = exc


def buffered(reader, size):
    """Background-thread prefetch (the host half of the reference's
    double-buffered reader, operators/reader/buffered_reader.cc). A
    source-reader exception re-raises in the consumer, never a silently
    truncated stream."""
    end = object()

    def r():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for e in reader():
                    q.put(e)
                q.put(end)
            except BaseException as exc:  # propagate to consumer
                q.put(_ReaderError(exc))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            # same starvation signal as reader.DataLoader: time the
            # consumer spends blocked on the prefetch queue
            t0 = time.perf_counter()
            e = q.get()
            STAT_OBSERVE("reader.batch_wait_seconds",
                         time.perf_counter() - t0)
            STAT_SET("reader.queue_depth", q.qsize())
            if e is end:
                return
            if isinstance(e, _ReaderError):
                raise e.exc
            STAT_ADD("reader.batches")
            yield e
    return r


def firstn(reader, n):
    def r():
        return itertools.islice(reader(), n)
    return r


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Thread-pool map over a reader (reference uses threads too)."""
    end = object()

    def r():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            try:
                for i, e in enumerate(reader()):
                    in_q.put((i, e))
                for _ in range(process_num):
                    in_q.put(end)
            except BaseException as exc:
                out_q.put(_ReaderError(exc))  # surface + unblock consumer

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, e = item
                try:
                    out_q.put((i, mapper(e)))
                except BaseException as exc:
                    out_q.put(_ReaderError(exc))
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, _ReaderError):
                raise item.exc
            i, v = item
            if not order:
                yield v
            else:
                pending[i] = v
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]
    return r


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """API-compatible stand-in running the readers in threads: jax's
    runtime does not survive fork(), the reference's mechanism."""
    return buffered(chain(*readers), queue_size)


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (python/paddle/batch.py)."""
    def r():
        b = []
        for e in reader():
            b.append(e)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return r

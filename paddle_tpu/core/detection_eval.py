"""Shared detection-mAP evaluation math (detection_map_op.h:308-475).

One implementation of the greedy score-ranked matching and the AP
interpolation, used by BOTH the detection_map op's host callback
(ops/parity_final.py) and the streaming metrics.DetectionMAP — a
semantics fix lands in exactly one place. The independent test witness
(tests/op_expects.py) deliberately does NOT use this module.
"""
from __future__ import annotations

import numpy as np

__all__ = ["match_class", "average_precision"]


def match_class(dets, gts, difficult, thr, evaluate_difficult):
    """Greedy matching of one image's one-class detections to its GTs.

    dets: [M, 5] (score, xmin, ymin, xmax, ymax) in any order;
    gts: [N, 4]; difficult: [N] bool. Returns [(score, flag)] with
    flag 1 = true positive, 0 = false positive; detections matching a
    difficult GT under evaluate_difficult=False produce NO record
    (CalcTrueAndFalsePositive, detection_map_op.h:391-403). Matching is
    strict `overlap > thr` with predictions clipped to [0,1] (ClipBBox)
    and each GT consumed by at most one detection.
    """
    dets = np.asarray(dets, np.float32).reshape(-1, 5)
    gts = np.asarray(gts, np.float32).reshape(-1, 4)
    difficult = np.asarray(difficult, bool).reshape(-1)
    order = np.argsort(-dets[:, 0], kind="stable")
    used = np.zeros(len(gts), bool)
    records = []
    for row in dets[order]:
        score = float(row[0])
        if len(gts) == 0:
            records.append((score, 0))
            continue
        b = np.clip(row[1:5], 0.0, 1.0)
        x1 = np.maximum(gts[:, 0], b[0])
        y1 = np.maximum(gts[:, 1], b[1])
        x2 = np.minimum(gts[:, 2], b[2])
        y2 = np.minimum(gts[:, 3], b[3])
        inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        area_g = (gts[:, 2] - gts[:, 0]) * (gts[:, 3] - gts[:, 1])
        area_b = (b[2] - b[0]) * (b[3] - b[1])
        iou = inter / np.maximum(area_g + area_b - inter, 1e-10)
        j = int(np.argmax(iou))
        if iou[j] > thr:
            if not evaluate_difficult and difficult[j]:
                continue  # difficult match: neither tp nor fp
            if used[j]:
                records.append((score, 0))
            else:
                used[j] = True
                records.append((score, 1))
        else:
            records.append((score, 0))
    return records


def average_precision(records, npos, ap_type):
    """AP from (score, tp-flag) records + the class positive count.
    ap_type 'integral' (reference default) or '11point' (VOC2007);
    CalcMAP, detection_map_op.h:414-475."""
    if npos == 0 or not records:
        return None
    recs = sorted(records, key=lambda r: -r[0])
    tp = np.cumsum([r[1] for r in recs])
    prec = tp / (np.arange(len(recs)) + 1)
    rec = tp / npos
    if ap_type == "11point":
        return sum(
            (prec[rec >= t].max() if (rec >= t).any() else 0.0) / 11.0
            for t in np.linspace(0, 1, 11))
    ap, prev = 0.0, 0.0
    for p, r in zip(prec, rec):
        if abs(r - prev) > 1e-6:
            ap += p * abs(r - prev)
        prev = r
    return float(ap)

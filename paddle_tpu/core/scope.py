"""Scope: name -> value store for persistable state.

Reference analogue: framework::Scope (scope.h:46) holding type-erased
Variables. Here a Scope maps var names to device arrays (jax.Array) or host
numpy arrays; the Executor donates the persistable sub-dict into each jitted
step so parameter updates are in-place at the XLA buffer level — the
functional-JAX answer to the reference's mutable-Scope optimizer kernels.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

import numpy as np



# Scope pool (reference: framework/scope_pool.{h,cc} — tracks every
# Python-created Scope so leaked ones can be cleared deterministically,
# the notebook/REPL hygiene hook exposed as core._ScopePool in pybind).
# Entries are weak: a Scope dies normally with its last reference; the
# pool only lets you bulk-release the arrays of whatever is still alive.
import weakref as _weakref

_scope_pool = _weakref.WeakSet()


def _pool_register(scope):
    _scope_pool.add(scope)


def scope_pool_size() -> int:
    return len(_scope_pool)


def clear_scope_pool():
    """Drop every tracked scope's contents (device buffers become
    collectable) — reference ScopePool::Clear. The global scope is
    emptied but stays usable."""
    for s in list(_scope_pool):
        s._vars.clear()
        s.drop_kids()


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, object] = {}
        self.parent = parent
        self._kids = []
        _pool_register(self)

    def var(self, name):
        """Create-if-missing (scope.h:62 Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return name

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has(self, name):
        s = self
        while s is not None:
            if name in s._vars and s._vars[name] is not None:
                return True
            s = s.parent
        return False

    def set(self, name, value):
        self._vars[name] = value

    def get(self, name):
        v = self.find_var(name)
        if v is None:
            raise KeyError(f"var {name!r} not initialised in scope")
        return v

    def get_numpy(self, name) -> np.ndarray:
        return np.asarray(self.get(name))

    def new_scope(self) -> "Scope":
        kid = Scope(parent=self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids.clear()

    def names(self):
        return list(self._vars)

    def delete(self, name):
        self._vars.pop(name, None)


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope() -> Scope:
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()

"""Dtype registry for the Program IR.

The reference keeps dtypes in the VarType proto
(/root/reference/paddle/fluid/framework/framework.proto:105). Here dtypes are
plain strings canonicalised to numpy/jax dtypes; bf16 is first-class because
it is the native TPU matmul type.
"""
from __future__ import annotations

import numpy as np

try:  # jax.numpy provides bfloat16 via ml_dtypes
    import ml_dtypes

    bfloat16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    import jax.numpy as jnp

    bfloat16 = np.dtype(jnp.bfloat16)

_CANON = {
    "float32": np.dtype("float32"),
    "fp32": np.dtype("float32"),
    "float64": np.dtype("float64"),
    "fp64": np.dtype("float64"),
    "float16": np.dtype("float16"),
    "fp16": np.dtype("float16"),
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "int8": np.dtype("int8"),
    "uint8": np.dtype("uint8"),
    "int16": np.dtype("int16"),
    "int32": np.dtype("int32"),
    "int64": np.dtype("int64"),
    "bool": np.dtype("bool"),
}


def convert_dtype(dtype) -> str:
    """Canonicalise any dtype spec (str, np.dtype, jnp dtype) to a string name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        if dtype not in _CANON:
            raise ValueError(f"unsupported dtype {dtype!r}")
        return str(np.dtype(_CANON[dtype]))
    d = np.dtype(dtype)
    return "bfloat16" if d == bfloat16 else d.name


def as_np_dtype(dtype) -> np.dtype:
    name = convert_dtype(dtype)
    return _CANON[name] if name in _CANON else np.dtype(name)


def is_floating(dtype) -> bool:
    name = convert_dtype(dtype)
    return name in ("float16", "float32", "float64", "bfloat16")

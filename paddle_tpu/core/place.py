"""Places: device selection.

Reference analogue: platform::Place variant (place.h:79). The north star
(BASELINE.json) asks for an XLAPlace alongside CPUPlace; TPUPlace is an alias
of XLAPlace bound to the TPU backend. A Place resolves to a concrete
jax.Device; the Executor uses it for jit backend selection and host->device
transfer of feeds.
"""
from __future__ import annotations

import jax


class Place:
    device_kind = "cpu"

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        # local_devices, not devices: in a multi-process (multi-host)
        # job jax.devices() lists every process's chips, and pinning
        # the single-device executor to another process's device makes
        # its outputs unfetchable from this one
        devs = jax.local_devices(backend=self.backend()) \
            if self.backend() else jax.local_devices()
        return devs[self.device_id]

    def backend(self):
        return None

    def is_cpu_place(self):
        return isinstance(self, CPUPlace)

    def is_xla_place(self):
        return isinstance(self, XLAPlace)


class CPUPlace(Place):
    device_kind = "cpu"

    def backend(self):
        return "cpu"


class XLAPlace(Place):
    """First-class accelerator place: whatever jax's default backend is."""
    device_kind = "xla"

    def backend(self):
        return None


class TPUPlace(XLAPlace):
    device_kind = "tpu"


# Compat alias: reference code says CUDAPlace; on this framework it means
# "the accelerator" (place.h:26 CUDAPlace -> XLAPlace per BASELINE.json).
CUDAPlace = XLAPlace
CUDAPinnedPlace = CPUPlace


def default_place() -> Place:
    try:
        kind = jax.devices()[0].platform
    except RuntimeError:
        kind = "cpu"
    return CPUPlace() if kind == "cpu" else XLAPlace()

"""Version-compat shims over moving jax APIs.

The parallel layer targets the current jax surface (jax.shard_map with
check_vma/axis_names, jax.lax.pcast); older installs (<=0.4.x) keep
shard_map in jax.experimental with check_rep and no axis_names, and
have no pcast. These wrappers let one call site serve both, so the
package imports (and the non-parallel 95% of it runs) regardless of
which jax the container bakes in.
"""
from __future__ import annotations

__all__ = ["shard_map", "pcast", "axis_size"]


def shard_map(fn, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None):
    """jax.shard_map when available, else jax.experimental.shard_map.

    Mapping to the old API: check_vma -> check_rep; axis_names={a, ...}
    -> auto=<every other mesh axis> (the old spelling of "only map
    these axes"). When falling back with check_vma unset, replication
    checking is disabled — the old checker predates the varying-type
    system the new-API callers are written against.
    """
    import jax
    new = getattr(jax, "shard_map", None)
    if new is not None:
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return new(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kw)
    from jax.experimental.shard_map import shard_map as old
    kw = {"check_rep": bool(check_vma) if check_vma is not None
          else False}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kw["auto"] = auto
    sm = old(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
             **kw)
    if kw.get("auto"):
        # the old eager impl rule rejects non-empty auto outright
        # (shard_map.py: `if auto: raise NotImplementedError`); only the
        # jit lowering path partitions auto axes, so force it
        sm = jax.jit(sm)
    return sm


def axis_size(axis_name):
    """jax.lax.axis_size when available; psum(1, axis) otherwise (old
    jax constant-folds a psum of a Python scalar to the static axis
    size, so both spellings yield a concrete int inside shard_map)."""
    import jax
    f = getattr(jax.lax, "axis_size", None)
    if f is not None:
        return f(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x, axis_name, to):
    """jax.lax.pcast when available; identity otherwise (pre-varying-type
    jax has no device-varying cast — with replication checks off the
    cast is unnecessary)."""
    import jax
    f = getattr(jax.lax, "pcast", None)
    if f is None:
        return x
    return f(x, axis_name, to=to)

"""Runtime flag registry + environment bootstrap.

Reference: the 136 gflags in platform/flags.cc:33-449 (DEFINE_* at a
central site, `DECLARE_*` at use sites) exported to Python via
core.globals, and the env bootstrap `read_env_flags` in
python/paddle/fluid/__init__.py:165 which imports `FLAGS_*` environment
variables at package import.

TPU-first differences: most reference flags configure subsystems XLA owns
outright (CUDA allocator fractions, cudnn autotune, NCCL rings), so the
set here is the flags that have a real knob in THIS runtime, plus a small
compatibility tier of reference names that are accepted, stored, and
documented as no-ops (so reference scripts that set them keep running).

Usage:
    from paddle_tpu.core.flags import FLAGS
    if FLAGS.check_nan_inf: ...
    FLAGS.executor_cache_capacity = 16

    # paddle-compatible API (core.globals analogue):
    fluid.get_flags(["FLAGS_check_nan_inf"])
    fluid.set_flags({"FLAGS_check_nan_inf": True})

Environment: `FLAGS_<name>=<value>` is read once at import (bools accept
0/1/true/false). `paddle_tpu.core.flags.reload_from_env()` re-reads.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List

__all__ = ["FLAGS", "DEFINE_bool", "DEFINE_int32", "DEFINE_int64",
           "DEFINE_double", "DEFINE_string", "get_flags", "set_flags",
           "flag_info", "reload_from_env"]


class _Flag:
    __slots__ = ("name", "default", "value", "ftype", "help", "noop",
                 "traced")

    def __init__(self, name, default, ftype, help_, noop=False,
                 traced=False):
        self.name = name
        self.default = default
        self.value = default
        self.ftype = ftype
        self.help = help_
        self.noop = noop
        # traced flags are baked into jitted executables; their values
        # join the executor cache key (trace_signature)
        self.traced = traced


_REGISTRY: Dict[str, _Flag] = {}
_LOCK = threading.Lock()


def _define(name, default, ftype, help_, noop=False, traced=False):
    with _LOCK:
        if name in _REGISTRY:
            raise ValueError(f"flag {name!r} already defined")
        _REGISTRY[name] = _Flag(name, default, ftype, help_, noop, traced)
    _load_one_from_env(name)
    return _REGISTRY[name]


def DEFINE_bool(name, default, help_="", traced=False):
    return _define(name, bool(default), bool, help_, traced=traced)


def DEFINE_int32(name, default, help_="", traced=False):
    return _define(name, int(default), int, help_, traced=traced)


DEFINE_int64 = DEFINE_int32


def DEFINE_double(name, default, help_="", traced=False):
    return _define(name, float(default), float, help_, traced=traced)


def DEFINE_string(name, default, help_="", traced=False):
    return _define(name, str(default), str, help_, traced=traced)


def _parse(ftype, raw: str):
    if ftype is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return ftype(raw)


def _load_one_from_env(name):
    raw = os.environ.get(f"FLAGS_{name}")
    if raw is not None:
        f = _REGISTRY[name]
        try:
            f.value = _parse(f.ftype, raw)
        except (ValueError, TypeError):
            # a bad env value must not make the package unimportable
            import warnings
            warnings.warn(
                f"ignoring malformed environment variable FLAGS_{name}="
                f"{raw!r} (expected {f.ftype.__name__}); keeping "
                f"{f.value!r}")


def reload_from_env():
    """Re-read every FLAGS_* environment variable (read_env_flags)."""
    for name in _REGISTRY:
        _load_one_from_env(name)


class _FlagsNamespace:
    """Attribute access: FLAGS.check_nan_inf. Unknown names raise."""

    def __getattr__(self, name):
        try:
            return _REGISTRY[name].value
        except KeyError:
            raise AttributeError(f"unknown flag {name!r}") from None

    def __setattr__(self, name, value):
        f = _REGISTRY.get(name)
        if f is None:
            raise AttributeError(f"unknown flag {name!r}")
        f.value = _parse(f.ftype, value) if isinstance(value, str) \
            else f.ftype(value)

    def __dir__(self):
        return sorted(_REGISTRY)


FLAGS = _FlagsNamespace()


def get_flags(names) -> Dict[str, Any]:
    """fluid.get_flags(["FLAGS_x", ...]) -> {name: value}."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {n!r}")
        out[n] = _REGISTRY[key].value
    return out


def set_flags(kv: Dict[str, Any]):
    """fluid.set_flags({"FLAGS_x": v, ...})."""
    for n, v in kv.items():
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {n!r}")
        setattr(FLAGS, key, v)


def trace_signature() -> tuple:
    """Values of every traced=True flag (baked into jitted executables).
    Executor cache keys include this so set_flags invalidates stale
    compilations instead of being silently ignored. Derived from the
    registry: a new traced flag is covered automatically."""
    return tuple(f.value for _, f in sorted(_REGISTRY.items()) if f.traced)


def flag_handle(name: str) -> _Flag:
    """The mutable _Flag record for `name` (internal). The monitor's
    disabled fast path caches this handle so every STAT_* call costs one
    attribute read instead of a registry lookup."""
    return _REGISTRY[name]


def flag_info() -> List[dict]:
    """All flags with metadata (for docs / debugging)."""
    return [{"name": f.name, "value": f.value, "default": f.default,
             "type": f.ftype.__name__, "help": f.help, "noop": f.noop}
            for f in _REGISTRY.values()]


# ---------------------------------------------------------------------------
# Flag definitions — the live knobs
# ---------------------------------------------------------------------------

DEFINE_bool(
    "check_nan_inf", False,
    "Debug mode: after every lowered op, verify each floating-point "
    "output is finite via an ordered host callback; raises naming the op "
    "and output var. Reference: operator.cc:820-822 / flags.cc:44. "
    "Heavy — debug only.", traced=True)

DEFINE_int32(
    "executor_cache_capacity", 64,
    "Max compiled executables kept per Executor (LRU evicted). Each entry "
    "is one (program fingerprint, feed shapes, fetches) specialization. "
    "Reference analogue: the per-program Prepare cache in executor.py.")

DEFINE_string(
    "prng_impl", "",
    "PRNG implementation for stateful ops (dropout etc.): '' = jax "
    "default (threefry2x32, splittable, slowest), 'rbg' = XLA "
    "RngBitGenerator backed by the TPU hardware RNG (much faster mask "
    "generation, still reproducible per (seed, step, op)), 'unsafe_rbg' "
    "= fastest, weakest folding. Reference analogue: the cuRAND-backed "
    "dropout kernels vs the CPU Philox path.", traced=True)

DEFINE_int32(
    "reader_queue_depth", 2,
    "Default host infeed queue capacity for DataLoader/PyReader when the "
    "user does not pass one (reader double-buffering depth). Reference: "
    "buffered_reader.cc double-buffer + pybind queue capacity.")

DEFINE_int32(
    "flash_attention_block_q", 512,
    "Fallback q-block tile for the Pallas flash-attention kernel when "
    "the op attr is unset AND the autotune cache has no entry for the "
    "shape (FLAGS_flash_autotune). Multiples of 128 only; clamped to "
    "the largest divisor of the (padded) sequence. 512 is the measured "
    "v5e winner at seq 512/1024/2048 — 2x faster fwd+bwd than XLA "
    "composed attention, where 128 was 2-4x SLOWER (PERF.md r05 "
    "attention microbench; docs/attention_tuning.md).", traced=True)

DEFINE_int32(
    "flash_attention_block_k", 512,
    "Fallback k-block tile for the Pallas flash-attention kernel when "
    "the op attr is unset and the autotune cache misses. Multiples of "
    "128 only; clamped like block_q. See flash_attention_block_q for "
    "the measured basis.", traced=True)

DEFINE_string(
    "flash_autotune", "cached",
    "Flash-attention tile autotuner mode (ops/pallas/autotune.py): "
    "'off' = flags/attrs only; 'cached' (default) = consult the "
    "process memo + persistent JSON cache but never tune (a miss falls "
    "back to FLAGS_flash_attention_block_{q,k} — CPU/tier-1 runs pay "
    "one dict lookup, no sweep); 'full' = on a cache miss time the "
    "{128,256,512} candidate grid on the real device, memoize and "
    "persist the winner. Interpret/CPU mode never sweeps.", traced=True)

DEFINE_string(
    "flash_autotune_cache", "",
    "Path of the persistent flash-tile cache (JSON). Empty = "
    "flash_autotune.json alongside the JAX compilation cache dir, or "
    "~/.cache/paddle_tpu when no compilation cache is configured. "
    "Seed it from real chip time with tools/attn_micro.py "
    "--emit-cache.")

DEFINE_bool(
    "pallas_interpret", False,
    "Force Pallas kernels into interpret mode even on TPU (debugging "
    "numerics; very slow).", traced=True)

DEFINE_bool(
    "op_trace_scopes", True,
    "Wrap each lowered op's emission in jax.named_scope("
    "'{op.type}:{block}/{op_idx}') so XPlane device traces, HLO dumps, "
    "and compiled-HLO op_name metadata attribute back to Program ops "
    "(reference: platform/profiler.cc per-op RecordEvent). Scopes are "
    "trace/metadata only — no runtime cost — so the default is on; "
    "turn off to diff HLO text across op reorderings.", traced=True)

DEFINE_string(
    "program_verify", "warn",
    "Static program verification (paddle_tpu/analysis) before the "
    "executor or serving engine spends a compile: 'off' = skip; 'warn' "
    "(default) = verify once per (program fingerprint, feeds, fetches) "
    "and surface findings as one summarized warning; 'error' = raise "
    "ProgramVerificationError on error-severity findings — with "
    "'{op_type}:{block}/{op_idx}' provenance — before any executable "
    "is built or cached. Zero device work either way: shape/dtype "
    "inference runs jax.eval_shape over each op's lowering. Rule "
    "catalog: docs/static_analysis.md; CLI: tools/program_lint.py.")

DEFINE_int32(
    "graph_opt_level", 1,
    "Program-IR optimization before lowering (analysis/passes): 0 = "
    "compile the program as built; 1 (default) = dead-op elimination, "
    "constant folding, and CSE on a verified clone; 2 adds elementwise-"
    "chain fusion (consecutive chains merge into one fused_elementwise "
    "op, falling back to a shared jax.named_scope when a merge gate "
    "fails) and the inplace/donation planner (per-var "
    "jax.jit donation of hazard-free optimizer state). The optimized "
    "program must re-verify clean (error semantics) before it replaces "
    "the original, and it is what the executable cache is keyed on. "
    "Catalog: docs/graph_passes.md.", traced=True)

DEFINE_int64(
    "memory_budget_bytes", 0,
    "HBM budget for the static memory gate (analysis/memory.py). 0 "
    "(default) = auto: use the device's reported bytes_limit "
    "(core.memory.device_memory_stats) when the backend reports one, "
    "otherwise no budget — CPU backends report nothing, so the gate "
    "never fires there. -1 = never apply a budget even when the device "
    "reports a limit. Any positive value is the budget in bytes. "
    "PTV050 fires when a program's estimated peak exceeds it, PTV051 "
    "when one tensor alone does. Docs: docs/memory_planning.md.")

DEFINE_string(
    "memory_gate", "error",
    "The pre-compile OOM gate (FLAGS_program_verify's sibling for the "
    "memory band, analysis/memory.py): 'off' = skip the static memory "
    "analysis; 'warn' = analyze once per (fingerprint, feed shapes, "
    "fetches, budget) and surface PTV05x findings as one summarized "
    "warning; 'error' (default) = raise ProgramVerificationError on "
    "PTV050/PTV051 — in Executor._resolve_step BEFORE the executable "
    "cache records a miss, and in ServingEngine.warmup before any "
    "ladder cell compiles — so a program that cannot fit is rejected "
    "with zero compiles attempted. Estimates with unresolved dynamic "
    "dims are documented lower bounds and the finding says so "
    "(Spec.nbytes). Docs: docs/memory_planning.md.")

DEFINE_string(
    "sharding_verify", "warn",
    "The pre-compile sharding gate (analysis/sharding.py — the PTV06x "
    "sibling of FLAGS_program_verify / FLAGS_memory_gate): 'off' = "
    "skip; 'warn' (default) = propagate the SpecLayout through the "
    "program graph once per (fingerprint, mesh, feed shapes, fetches) "
    "and surface PTV060-063 findings as one summarized warning; "
    "'error' = raise ProgramVerificationError on PTV060 layout-"
    "inconsistent ops — in Executor._resolve_step BEFORE the "
    "executable cache records a miss, and in ServingEngine.warmup "
    "before any ladder cell compiles. The gate only engages when a "
    "layout is in scope (the sharded-exec SpecLayout, or "
    "FLAGS_sharded_mesh is set); with no mesh it is a no-op. The same "
    "pass prices the implied collectives into a predicted "
    "collective_bytes_per_step (docs/sharding.md, "
    "docs/static_analysis.md).")

DEFINE_bool(
    "buffer_reuse", True,
    "Enable the buffer-reuse rewrite (analysis/passes/reuse.py) when "
    "FLAGS_graph_opt_level >= 2: transient same-shape/dtype vars with "
    "strictly disjoint liveness intervals collapse onto one shared "
    "buffer (the reference framework's memory_optimize_pass), lowering "
    "the static peak estimate the memory gate enforces. Off = level 2 "
    "keeps fusion+donation but skips the reuse rewrite (the sweep "
    "driver's _reuse_on/_reuse_off A/B pair).", traced=True)

DEFINE_bool(
    "flight_recorder", True,
    "Keep a bounded in-memory ring of per-step flight records (step "
    "index, program, cache hit/miss, timings, stat deltas, NaN "
    "provenance) that monitor.dump_flight_recorder writes as JSONL on "
    "unhandled exception / SIGTERM (monitor.install_flight_recorder) "
    "or on demand. One dict append per step — cheap enough to leave "
    "on; the black box that turns 'the run died' into 'step N died'.")

DEFINE_int32(
    "flight_recorder_capacity", 512,
    "Max records kept in the flight-recorder ring (oldest dropped "
    "first). 512 steps of context is hours of large-model training "
    "and a few KB of host memory.")

DEFINE_string(
    "flight_recorder_path", "",
    "Default path for monitor.dump_flight_recorder / "
    "install_flight_recorder when no explicit path is given. Empty = "
    "flight_recorder.jsonl in the working directory.")

DEFINE_int32(
    "monitor_http_port", 0,
    "When > 0, monitor.serve_prometheus() binds a stdlib HTTP scrape "
    "endpoint on 127.0.0.1:<port> serving prometheus_text() (started "
    "automatically by monitor.start_exporter). 0 = disabled.")

DEFINE_bool(
    "enable_monitor", False,
    "Enable the runtime stats registry (paddle_tpu/monitor.py): "
    "executor compile/step/feed timing, reader queue stats, device "
    "memory gauges. Off = every STAT_* call is a near-zero-cost no-op. "
    "Reference: the always-on STAT registry of platform/monitor.h, made "
    "opt-in here because host callbacks are the expensive resource on "
    "TPU.")

DEFINE_string(
    "monitor_export_path", "",
    "Default JSONL file for monitor snapshots (append mode, one JSON "
    "object per line). Used by monitor.snapshot_to_jsonl / "
    "start_exporter when no explicit path is given; bench.py and "
    "tools/profile_step.py write here when set.")

DEFINE_double(
    "monitor_flush_interval_s", 10.0,
    "Interval of the background JSONL snapshot exporter "
    "(monitor.start_exporter). Crash-safety knob: a run killed by an "
    "external timeout still leaves snapshots this fresh.")

DEFINE_int32(
    "serving_max_batch_size", 8,
    "Default EngineConfig.max_batch_size: the most request rows the "
    "serving engine coalesces into one padded batch (must fit the "
    "largest batch bucket). Serving analogue of the reference "
    "predictor pool size.")

DEFINE_int32(
    "serving_max_wait_us", 2000,
    "Default EngineConfig.max_wait_us: how long (microseconds) a "
    "partially-filled batch may wait for co-batchable requests before "
    "the worker flushes it. The latency/throughput dial of the dynamic "
    "batcher.")

DEFINE_int32(
    "serving_queue_capacity", 256,
    "Default EngineConfig.queue_capacity: max request rows pending in "
    "the dynamic batcher before submissions are rejected with "
    "QueueFullError (backpressure instead of unbounded queueing).")

DEFINE_bool(
    "gen_paged_kv", True,
    "Generation engine KV layout: True (default) = block-table paged "
    "KV cache (serving/kv_blocks.py + models/gpt."
    "build_paged_decode_step) with prefix caching and chunked prefill; "
    "False = the PR-7 contiguous [max_slots, max_seq] slab decode, "
    "retained for the paged-vs-slab A/B (sweep_driver "
    "gen_paged_vs_slab pair). Host-side program choice only — not part "
    "of any executable cache key.")

DEFINE_int32(
    "gen_kv_block_size", 16,
    "Paged KV cache: tokens per physical block. Larger blocks mean "
    "fewer gather indices per step but coarser prefix-cache "
    "granularity (only FULL prompt blocks are content-hash shareable) "
    "and more tail waste per sequence. Also the chunk width of the "
    "chunked-prefill executable.")

DEFINE_int32(
    "gen_kv_pool_blocks", 0,
    "Paged KV cache: physical blocks in the pool (one is reserved as "
    "the scratch block). 0 (default) = derive: from "
    "FLAGS_gen_kv_pool_bytes when set, else full capacity "
    "(max_slots x ceil(max_seq/block_size) + scratch). This — not "
    "max_slots x max_seq — is what bounds peak KV HBM; the static "
    "memory planner prices the pool persistables directly.")

DEFINE_int64(
    "gen_kv_pool_bytes", 0,
    "Paged KV cache: HBM budget for the K/V pools across all layers; "
    "the engine sizes the pool as budget // block_bytes blocks. 0 = "
    "unset (FLAGS_gen_kv_pool_blocks or full capacity applies). The "
    "knob the gen_paged_vs_slab A/B holds fixed while comparing "
    "sustainable slot counts.")

DEFINE_bool(
    "gen_spec_decode", False,
    "Generation engine default for speculative decoding "
    "(serving/spec_decode.py): when True a paged engine builds the "
    "third fixed-shape executable (the [max_slots, k+1] batched verify "
    "step) at start() and drafts with the host-side n-gram / "
    "prompt-lookup drafter every decode iteration. Per-request "
    "GenerationRequest.spec_decode overrides (None = this default). "
    "Host-side program choice only — never part of an executable cache "
    "key; post_warmup_compiles() stays 0 either way.")

DEFINE_int32(
    "spec_decode_k", 4,
    "Speculative decoding: maximum draft tokens proposed per slot per "
    "iteration. The verify executable is compiled at [max_slots, k+1] "
    "(k drafts + the committed token), so changing k changes the ONE "
    "extra warmup compile, not the steady state. Larger k amortizes "
    "more dispatch overhead on repetitive text but wastes verify "
    "compute when acceptance is low.")

DEFINE_int32(
    "spec_decode_ngram", 3,
    "Speculative decoding: longest context suffix the n-gram / "
    "prompt-lookup drafter matches against the slot's prompt + "
    "generated tokens. Matching tries n down to 1 and proposes the "
    "tokens that followed the most recent earlier occurrence; 0 "
    "disables drafting (the verify path then never dispatches).")

DEFINE_bool(
    "spec_decode_adaptive", True,
    "Acceptance-aware adaptive draft length (serving/spec_decode.py "
    "update_spec_k): each slot tracks an EWMA of its measured draft "
    "acceptance rate and shrinks its per-iteration draft budget toward "
    "1 when acceptance stops paying for the verify premium (EWMA < "
    "FLAGS_spec_adapt_low), growing it back toward FLAGS_spec_decode_k "
    "when acceptance recovers (EWMA > FLAGS_spec_adapt_high). Host-side "
    "only: the verify executable stays compiled at [max_slots, k+1] and "
    "accepted outputs are unchanged — only the proposed draft length "
    "moves.")

DEFINE_double(
    "spec_adapt_low", 0.3,
    "Adaptive spec_k shrink threshold: when a slot's acceptance-rate "
    "EWMA drops below this, its draft budget shrinks by 1 (floor 1).")

DEFINE_double(
    "spec_adapt_high", 0.8,
    "Adaptive spec_k grow threshold: when a slot's acceptance-rate "
    "EWMA rises above this, its draft budget grows by 1 (cap "
    "FLAGS_spec_decode_k).")

DEFINE_double(
    "serving_default_timeout_ms", 1000.0,
    "Default EngineConfig.default_timeout_ms: per-request deadline "
    "applied when a submission does not carry its own; a request still "
    "queued past it fails with DeadlineExceededError. 0 = no deadline.")

DEFINE_int32(
    "serving_http_port", 0,
    "Default EngineConfig.http_port for serving.serve(): the port of "
    "the JSON front end (/v1/predict, /healthz, /metrics). 0 binds an "
    "ephemeral port.")

DEFINE_string(
    "profiler_trace_dir", "",
    "When set, fluid.profiler writes chrome-trace/XPlane dumps here by "
    "default. Reference: FLAGS profile_path (flags.cc).")

DEFINE_string(
    "fault_spec", "",
    "Deterministic fault-injection spec (paddle_tpu/resilience/"
    "faults.py): comma-separated kind:param list, e.g. "
    "'step_nan:p=0.01,slow_step:ms=500,transient_fail:p=0.02,"
    "preempt_at:step=40'. Empty (default) = injection disabled, zero "
    "overhead. Grammar and semantics: docs/resilience.md.")

DEFINE_int32(
    "fault_seed", 0,
    "Seed of the fault-injection RNG. Decisions derive from (seed, "
    "site, per-site invocation counter), so a given spec+seed injects "
    "the same faults at the same steps regardless of timing or thread "
    "interleaving.")

DEFINE_int32(
    "retry_max_attempts", 3,
    "Default RetryPolicy attempt budget (paddle_tpu/resilience/"
    "retry.py): total tries, first included. Transient faults "
    "(TransientFault and friends) retry up to this many times with "
    "jittered exponential backoff; poison errors (ValueError, "
    "verification failures) never retry.")

DEFINE_double(
    "retry_base_ms", 10.0,
    "Default RetryPolicy base backoff (milliseconds): attempt n sleeps "
    "~base * 2^(n-1), jittered, capped by FLAGS_retry_max_ms.")

DEFINE_double(
    "retry_max_ms", 1000.0,
    "Default RetryPolicy backoff cap (milliseconds).")

DEFINE_int32(
    "serving_breaker_threshold", 5,
    "Circuit breaker (paddle_tpu/resilience/breaker.py): consecutive "
    "batch-execution failures before the serving/generation breaker "
    "trips CLOSED -> OPEN and submissions shed with OverloadedError "
    "(HTTP 503 + Retry-After). 0 disables the breaker.")

DEFINE_double(
    "serving_breaker_cooldown_ms", 1000.0,
    "How long an OPEN breaker sheds load before admitting half-open "
    "probe traffic. A successful probe closes the breaker; a failed "
    "one re-opens it for another cooldown.")

DEFINE_int32(
    "router_redispatch_budget", 2,
    "Multi-replica router (paddle_tpu/serving/router.py): how many "
    "times one request may be re-dispatched to a different replica "
    "after a retryable failure (replica death, 503 shed, connection "
    "reset) before the error is surfaced to the client. 0 disables "
    "failover.")

DEFINE_double(
    "router_probe_interval_s", 0.5,
    "Router health-probe cadence: every interval the router polls each "
    "replica's health (/healthz for --url replicas, engine.health() "
    "in-process) and updates its routing table. 0 disables active "
    "probing (passive failure accounting still runs).")

DEFINE_int32(
    "router_failure_threshold", 3,
    "Consecutive dispatch failures before the router's per-replica "
    "circuit breaker marks that replica unhealthy and routes around "
    "it. 0 disables the per-replica breaker.")

DEFINE_int32(
    "router_affinity_max", 4096,
    "Session-affinity table capacity: the router keeps at most this "
    "many session->replica pins, evicting the least recently used pin "
    "past the cap, so a long-running router's memory stays bounded "
    "under a stream of short-lived generation sessions.")

DEFINE_double(
    "router_drain_timeout_s", 30.0,
    "Hot-swap / deregister drain deadline: how long the router waits "
    "for a retired replica's in-flight requests to finish before "
    "stopping it anyway.")

DEFINE_bool(
    "router_disagg", False,
    "Disaggregated prefill/decode dispatch (paddle_tpu/serving/"
    "disagg.py): Router.generate() runs two-phase scheduling — pick a "
    "decode replica, and when the fleet prefix store says it does not "
    "already own the prompt's full-block chain, have a prefill-capable "
    "replica export the KV blocks over the wire and the decode replica "
    "adopt them before the decode dispatch. Off (default) = classic "
    "single-phase routing; transfer failures always fall back to the "
    "decode worker re-prefilling locally, so answers never change.")

DEFINE_int32(
    "disagg_fleet_prefix_max", 4096,
    "FleetPrefixStore capacity: at most this many chain-hash entries "
    "(hash -> owning replica names) are kept on the router, LRU-evicted "
    "past the cap. Eviction only forgets WHERE a prefix lives — the "
    "worst case is a redundant re-prefill, never a wrong answer.")

DEFINE_bool(
    "serving_nan_guard", True,
    "Serving engine output hygiene: verify every batch's float outputs "
    "are finite before scattering them to clients; a non-finite batch "
    "is treated as a transient fault (retried via RetryPolicy, then "
    "failed) instead of being served as a wrong answer.")

DEFINE_bool(
    "sharded_exec", False,
    "GSPMD sharded execution (paddle_tpu/parallel/layout.py): when a "
    "CompiledProgram runs data-parallel, attach a SpecLayout table over "
    "the FLAGS_sharded_mesh Mesh — feeds batch-shard on the data axis, "
    "optimizer moments and the weight update ZeRO-shard across replicas "
    "(arxiv 2004.13336), params optionally split on the model axis — "
    "and jit with the derived in/out_shardings. Off = legacy replicated "
    "data-parallel. Traced: flipping it recompiles.", traced=True)

DEFINE_string(
    "sharded_mesh", "",
    "Mesh shape for FLAGS_sharded_exec as 'dp' or 'dp,tp' (e.g. '8' or "
    "'4,2'); axis 0 is the data axis, axis 1 the model axis. Empty = "
    "the parallel.get_mesh() registry mesh (all devices, 1-D data "
    "axis). Traced: a shape change recompiles.", traced=True)

DEFINE_bool(
    "enable_trace", False,
    "Per-request distributed tracing (paddle_tpu/trace.py): spans with "
    "W3C traceparent propagation across the HTTP -> batcher -> engine "
    "-> executor path. Off, every trace entry point returns after one "
    "cached-flag read. Host-side only — never part of a compile cache "
    "key.")

DEFINE_double(
    "trace_sample", 0.05,
    "Head-sampling keep probability for request traces (decided once "
    "per root span). Tail rules OVERRIDE it: errored requests and "
    "requests slower than the rolling latency threshold are always "
    "kept. 1.0 keeps every trace.")

DEFINE_int32(
    "trace_ring_capacity", 8192,
    "Bounded in-process span ring: kept spans past this count evict "
    "oldest-first. Sized for post-mortem dumps, not long-term storage "
    "— export with trace.export_jsonl / export_chrome_tracing.")

DEFINE_double(
    "trace_tail_slow_ms", 0.0,
    "Absolute tail-sampling slow threshold (ms): any request whose "
    "e2e exceeds it is kept regardless of head sampling. 0 (default) "
    "= rolling p95 over the last trace window (keeps ~the slowest 5% "
    "once enough requests have completed).")

DEFINE_bool(
    "enable_goodput", False,
    "Run-level goodput accounting (paddle_tpu/goodput.py): classify "
    "ALL wall-clock of a training/bench run into exclusive categories "
    "(device_compute, compile, input_wait, feed_stage, fetch_sync, "
    "checkpoint_save/restore, retry_backoff, nan_rollback, "
    "preempt_drain, probe_wait, other) with the invariant that the "
    "categories sum to wall-clock. Off (default) = every goodput hook "
    "is one cached-flag read. Stats ride the monitor registry, so "
    "FLAGS_enable_monitor gates the exported goodput.* stats.")

DEFINE_double(
    "goodput_starved_ms", 50.0,
    "Input-starvation threshold: a training step whose reader batch "
    "wait exceeds this many milliseconds counts as input-starved "
    "(goodput.input_starved_steps) and feeds the default "
    "input_starvation burn-rate alert rule that goodput.start_run "
    "appends to FLAGS_alert_rules.")

DEFINE_string(
    "goodput_alert_windows", "15s,60s",
    "Multi-window spec of the default input_starvation burn-rate rule "
    "(short,long — both must breach before the alert fires, the "
    "monitor_alerts.py burn semantics). Only read when "
    "goodput.install_starvation_alert builds the default rule.")

DEFINE_string(
    "alert_rules", "",
    "Declarative SLO alert rules for paddle_tpu/monitor_alerts.py, "
    "semicolon-separated. Grammar per rule: "
    "'name:threshold:STAT OP VALUE[:for=DUR]' over a counter/gauge, "
    "'name:ratio:NUM/DEN OP VALUE[:for=DUR]' over two counters, or "
    "'name:burn:HIST:pQQ OP VALUE:windows=W1,W2' multi-window burn "
    "rate over a histogram percentile (fires only when EVERY window "
    "breaches). OP is one of > >= < <=; durations accept s/m/h "
    "suffixes. Empty (default) disables the evaluator entirely.")

DEFINE_double(
    "alert_eval_interval_s", 5.0,
    "Period of the background alert evaluator thread (seconds). Each "
    "tick snapshots the monitor registry once and evaluates every "
    "FLAGS_alert_rules rule against it; <= 0 disables the background "
    "thread (rules still evaluate via alerts.evaluate_once(), which "
    "tests drive with a fake clock).")

DEFINE_string(
    "alert_bundle_dir", "",
    "Directory for incident bundles: on each pending->firing "
    "transition the alert engine writes exactly one atomic JSON "
    "bundle correlating the rule, the full stats snapshot, breaching-"
    "bucket trace exemplars, the kept-trace ring, and the flight-"
    "recorder ring. Empty (default) = bundles disabled; alerts still "
    "fire and expose via /alertz and ALERTS exposition.")

DEFINE_int32(
    "alert_bundle_max_spans", 512,
    "Cap on kept-trace-ring spans embedded in one incident bundle "
    "(newest kept spans win, after breaching-bucket exemplar traces "
    "are included first). Bounds bundle size on busy servers.")

# ---------------------------------------------------------------------------
# Reference-flag compat surface (App. C parity target:
# platform/flags.cc:33-449 + the read_env_flags whitelist in
# python/paddle/fluid/__init__.py:165). Reference programs call
# fluid.set_flags / export FLAGS_* freely; every name in the inventory
# is accepted here. Flags marked no-op describe CUDA/CPU-runtime
# machinery that XLA/TPU absorbs (allocator strategies, cuDNN
# autotuning, NCCL dirs, eager deletion GC, ...) — they are settable,
# readable, and ignored, with the TPU-native equivalent named in the
# help text where one exists.
# ---------------------------------------------------------------------------

def _compat(name, default, help_=""):
    ftype = type(default)
    _define(name, default,
            ftype if ftype in (bool, int, float) else str,
            help_, noop=True)


for _name, _default, _help in [
    ("cpu_deterministic", False,
     "no-op: single jitted computation is deterministic"),
    ("allocator_strategy", "naive_best_fit",
     "no-op: device memory is XLA buffer assignment; host pool is "
     "native/src/allocator.h"),
    ("fast_check_nan_inf", False,
     "no-op: FLAGS_check_nan_inf covers both modes here"),
    ("collective_get_thread_num", 16, "no-op: XLA collectives"),
    ("communicator_fake_rpc", False, "no-op: test hook of the ref"),
    ("communicator_independent_recv_thread", True,
     "no-op: PS communicator threading (distributed/ps_server.py)"),
    ("communicator_is_sgd_optimizer", True, "no-op"),
    ("communicator_max_merge_var_num", 20, "no-op"),
    ("communicator_merge_sparse_bucket", 2000, "no-op"),
    ("communicator_merge_sparse_grad", True, "no-op"),
    ("communicator_min_send_grad_num_before_recv", 20, "no-op"),
    ("communicator_send_queue_size", 20, "no-op"),
    ("communicator_send_wait_times", 5, "no-op"),
    ("communicator_thread_pool_size", 5, "no-op"),
    ("conv_workspace_size_limit", 512,
     "no-op: XLA picks conv algorithms; no cuDNN workspace"),
    ("cudnn_batchnorm_spatial_persistent", False, "no-op: CUDA-only"),
    ("cudnn_deterministic", False,
     "no-op: XLA TPU executables are deterministic by construction"),
    ("cudnn_exhaustive_search", False, "no-op: CUDA-only"),
    ("cudnn_exhaustive_search_times", -1, "no-op: CUDA-only"),
    ("dist_threadpool_size", 0,
     "no-op: RPC concurrency is distributed/rpc.py thread-per-conn"),
    ("dygraph_debug", False, "no-op: use check_nan_inf / jax debug"),
    ("enable_parallel_graph", False,
     "no-op: multi-device execution is GSPMD, not graph replication"),
    ("fast_eager_deletion_mode", True,
     "no-op: buffer lifetime is XLA's; donation frees inputs"),
    ("fraction_of_cpu_memory_to_use", 1.0, "no-op"),
    ("fraction_of_gpu_memory_to_use", 0.92,
     "no-op: HBM budgeting is core/memory.py assert_hbm_within"),
    ("init_allocated_mem", False, "no-op"),
    ("initial_cpu_memory_in_mb", 500, "no-op"),
    ("inner_op_parallelism", 0, "no-op: XLA schedules ops"),
    ("io_threadpool_size", 100,
     "no-op: reader threads are reader.py + native data_feed.cc"),
    ("local_exe_sub_scope_limit", 256.0,
     "no-op: no per-device scopes (reference: double, MBytes)"),
    ("eager_delete_scope", True, "no-op: Scope GC is Python's"),
    ("enable_cublas_tensor_op_math", False, "no-op: CUDA-only"),
    ("fuse_parameter_groups_size", 3,
     "no-op: gradient fusion is XLA's; GradientMergeOptimizer covers "
     "the accumulation use case"),
    ("fuse_parameter_memory_size", -1, "no-op: same as groups_size"),
    ("gpu_allocator_retry_time", 2000, "no-op"),
    ("initial_gpu_memory_in_mb", 0, "no-op"),
    ("max_body_size", 2147483647,
     "no-op: distributed/rpc.py frames are length-prefixed without a "
     "hard cap"),
    ("print_sub_graph_dir", "",
     "no-op: graph dumps via debugger.draw_block_graphviz"),
    ("reader_queue_speed_test_mode", False,
     "no-op: test hook of the reference reader queue"),
    ("rpc_get_thread_num", 12, "no-op: thread-per-connection server"),
    ("rpc_prefetch_thread_num", 12, "no-op"),
    ("rpc_send_thread_num", 12, "no-op"),
    ("sync_nccl_allreduce", True,
     "no-op: XLA collectives are synchronous in-program ops"),
    ("free_idle_memory", False, "no-op"),
    ("limit_of_tmp_allocation", -1, "no-op"),
    ("memory_optimize_debug", "", "no-op: no memory-reuse pass to log"),
    ("times_excess_than_required_tmp_allocation", 2, "no-op"),
    ("memory_fraction_of_eager_deletion", 1.0, "no-op"),
    ("paddle_num_threads", 1, "no-op: host math is jax CPU"),
    ("pe_profile_fname", "", "no-op: use profiler.py traces"),
    ("reallocate_gpu_memory_in_mb", 0, "no-op"),
    ("rpc_deadline", 180000,
     "no-op: distributed/rpc.py uses socket timeouts"),
    ("rpc_disable_reuse_port", False, "no-op"),
    ("rpc_retry_bind_port", 3, "no-op"),
    ("rpc_retry_times", 3, "no-op"),
    ("rpc_server_profile_path", "./profile_ps", "no-op"),
    ("selected_gpus", "",
     "no-op: device selection is JAX_PLATFORMS / jax.devices()"),
    ("skip_fused_all_reduce_check", False, "no-op"),
    ("use_mkldnn", False, "no-op: CPU fallback is XLA:CPU"),
    ("use_ngraph", False, "no-op"),
    ("worker_update_interval_secs", 900, "no-op: PS heartbeat knob"),
    ("benchmark", False,
     "no-op: bench.py + profiler.py are the benchmark path"),
    ("eager_delete_tensor_gb", 0.0,
     "no-op: XLA buffer assignment frees dead buffers at compile "
     "time; donation covers step state"),
    ("enable_rpc_profiler", False, "no-op"),
    ("multiple_of_cupti_buffer_size", 1, "no-op: CUPTI is CUDA-only"),
    ("init_p2p", True, "no-op: ICI needs no P2P init"),
    ("cuda_dir", "", "no-op: dynload search path, CUDA-only"),
    ("cudnn_dir", "", "no-op"),
    ("nccl_dir", "", "no-op: collectives ride XLA/ICI"),
    ("mklml_dir", "", "no-op"),
    ("cupti_dir", "", "no-op"),
    ("use_pinned_memory", True, "no-op"),
    ("tracer_profile_fname", "", "no-op: dygraph tracing uses "
     "profiler.py"),
]:
    _compat(_name, _default, _help)

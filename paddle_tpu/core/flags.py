"""Runtime flag registry + environment bootstrap.

Reference: the 136 gflags in platform/flags.cc:33-449 (DEFINE_* at a
central site, `DECLARE_*` at use sites) exported to Python via
core.globals, and the env bootstrap `read_env_flags` in
python/paddle/fluid/__init__.py:165 which imports `FLAGS_*` environment
variables at package import.

TPU-first differences: most reference flags configure subsystems XLA owns
outright (CUDA allocator fractions, cudnn autotune, NCCL rings), so the
set here is the flags that have a real knob in THIS runtime, plus a small
compatibility tier of reference names that are accepted, stored, and
documented as no-ops (so reference scripts that set them keep running).

Usage:
    from paddle_tpu.core.flags import FLAGS
    if FLAGS.check_nan_inf: ...
    FLAGS.executor_cache_capacity = 16

    # paddle-compatible API (core.globals analogue):
    fluid.get_flags(["FLAGS_check_nan_inf"])
    fluid.set_flags({"FLAGS_check_nan_inf": True})

Environment: `FLAGS_<name>=<value>` is read once at import (bools accept
0/1/true/false). `paddle_tpu.core.flags.reload_from_env()` re-reads.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List

__all__ = ["FLAGS", "DEFINE_bool", "DEFINE_int32", "DEFINE_int64",
           "DEFINE_double", "DEFINE_string", "get_flags", "set_flags",
           "flag_info", "reload_from_env"]


class _Flag:
    __slots__ = ("name", "default", "value", "ftype", "help", "noop",
                 "traced")

    def __init__(self, name, default, ftype, help_, noop=False,
                 traced=False):
        self.name = name
        self.default = default
        self.value = default
        self.ftype = ftype
        self.help = help_
        self.noop = noop
        # traced flags are baked into jitted executables; their values
        # join the executor cache key (trace_signature)
        self.traced = traced


_REGISTRY: Dict[str, _Flag] = {}
_LOCK = threading.Lock()


def _define(name, default, ftype, help_, noop=False, traced=False):
    with _LOCK:
        if name in _REGISTRY:
            raise ValueError(f"flag {name!r} already defined")
        _REGISTRY[name] = _Flag(name, default, ftype, help_, noop, traced)
    _load_one_from_env(name)
    return _REGISTRY[name]


def DEFINE_bool(name, default, help_="", traced=False):
    return _define(name, bool(default), bool, help_, traced=traced)


def DEFINE_int32(name, default, help_="", traced=False):
    return _define(name, int(default), int, help_, traced=traced)


DEFINE_int64 = DEFINE_int32


def DEFINE_double(name, default, help_="", traced=False):
    return _define(name, float(default), float, help_, traced=traced)


def DEFINE_string(name, default, help_="", traced=False):
    return _define(name, str(default), str, help_, traced=traced)


def _parse(ftype, raw: str):
    if ftype is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return ftype(raw)


def _load_one_from_env(name):
    raw = os.environ.get(f"FLAGS_{name}")
    if raw is not None:
        f = _REGISTRY[name]
        try:
            f.value = _parse(f.ftype, raw)
        except (ValueError, TypeError):
            # a bad env value must not make the package unimportable
            import warnings
            warnings.warn(
                f"ignoring malformed environment variable FLAGS_{name}="
                f"{raw!r} (expected {f.ftype.__name__}); keeping "
                f"{f.value!r}")


def reload_from_env():
    """Re-read every FLAGS_* environment variable (read_env_flags)."""
    for name in _REGISTRY:
        _load_one_from_env(name)


class _FlagsNamespace:
    """Attribute access: FLAGS.check_nan_inf. Unknown names raise."""

    def __getattr__(self, name):
        try:
            return _REGISTRY[name].value
        except KeyError:
            raise AttributeError(f"unknown flag {name!r}") from None

    def __setattr__(self, name, value):
        f = _REGISTRY.get(name)
        if f is None:
            raise AttributeError(f"unknown flag {name!r}")
        f.value = _parse(f.ftype, value) if isinstance(value, str) \
            else f.ftype(value)

    def __dir__(self):
        return sorted(_REGISTRY)


FLAGS = _FlagsNamespace()


def get_flags(names) -> Dict[str, Any]:
    """fluid.get_flags(["FLAGS_x", ...]) -> {name: value}."""
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {n!r}")
        out[n] = _REGISTRY[key].value
    return out


def set_flags(kv: Dict[str, Any]):
    """fluid.set_flags({"FLAGS_x": v, ...})."""
    for n, v in kv.items():
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {n!r}")
        setattr(FLAGS, key, v)


def trace_signature() -> tuple:
    """Values of every traced=True flag (baked into jitted executables).
    Executor cache keys include this so set_flags invalidates stale
    compilations instead of being silently ignored. Derived from the
    registry: a new traced flag is covered automatically."""
    return tuple(f.value for _, f in sorted(_REGISTRY.items()) if f.traced)


def flag_info() -> List[dict]:
    """All flags with metadata (for docs / debugging)."""
    return [{"name": f.name, "value": f.value, "default": f.default,
             "type": f.ftype.__name__, "help": f.help, "noop": f.noop}
            for f in _REGISTRY.values()]


# ---------------------------------------------------------------------------
# Flag definitions — the live knobs
# ---------------------------------------------------------------------------

DEFINE_bool(
    "check_nan_inf", False,
    "Debug mode: after every lowered op, verify each floating-point "
    "output is finite via an ordered host callback; raises naming the op "
    "and output var. Reference: operator.cc:820-822 / flags.cc:44. "
    "Heavy — debug only.", traced=True)

DEFINE_int32(
    "executor_cache_capacity", 64,
    "Max compiled executables kept per Executor (LRU evicted). Each entry "
    "is one (program fingerprint, feed shapes, fetches) specialization. "
    "Reference analogue: the per-program Prepare cache in executor.py.")

DEFINE_string(
    "prng_impl", "",
    "PRNG implementation for stateful ops (dropout etc.): '' = jax "
    "default (threefry2x32, splittable, slowest), 'rbg' = XLA "
    "RngBitGenerator backed by the TPU hardware RNG (much faster mask "
    "generation, still reproducible per (seed, step, op)), 'unsafe_rbg' "
    "= fastest, weakest folding. Reference analogue: the cuRAND-backed "
    "dropout kernels vs the CPU Philox path.", traced=True)

DEFINE_int32(
    "reader_queue_depth", 2,
    "Default host infeed queue capacity for DataLoader/PyReader when the "
    "user does not pass one (reader double-buffering depth). Reference: "
    "buffered_reader.cc double-buffer + pybind queue capacity.")

DEFINE_int32(
    "flash_attention_block_q", 128,
    "Default q-block tile for the Pallas flash-attention kernel when the "
    "op attr does not specify one. Multiples of 128 only.", traced=True)

DEFINE_int32(
    "flash_attention_block_k", 128,
    "Default k-block tile for the Pallas flash-attention kernel when the "
    "op attr does not specify one. Multiples of 128 only.", traced=True)

DEFINE_bool(
    "pallas_interpret", False,
    "Force Pallas kernels into interpret mode even on TPU (debugging "
    "numerics; very slow).", traced=True)

DEFINE_string(
    "profiler_trace_dir", "",
    "When set, fluid.profiler writes chrome-trace/XPlane dumps here by "
    "default. Reference: FLAGS profile_path (flags.cc).")

# --- compatibility tier: accepted + stored, no effect on TPU ------------
for _name, _default, _help in [
    ("eager_delete_tensor_gb", 0.0,
     "no-op: XLA buffer assignment owns device memory lifetime"),
    ("fraction_of_gpu_memory_to_use", 0.92,
     "no-op: no CUDA allocator in this runtime"),
    ("cudnn_deterministic", False,
     "no-op: XLA:TPU compilation is deterministic"),
    ("allocator_strategy", "auto_growth",
     "no-op: kept for reference-script compatibility"),
    ("cpu_deterministic", False,
     "no-op: single jitted computation is deterministic"),
    ("local_exe_sub_scope_limit", 0.5,
     "no-op: no per-device sub-scopes; XLA owns live-range memory"),
]:
    f = _define(_name, _default,
                bool if isinstance(_default, bool)
                else float if isinstance(_default, float)
                else str if isinstance(_default, str) else int,
                _help, noop=True)

"""Op registry: op type -> JAX lowering + metadata.

Reference analogue: OpInfoMap + REGISTER_OPERATOR / REGISTER_OP_*_KERNEL
(/root/reference/paddle/fluid/framework/op_registry.h:199-270). On TPU there
is no per-device kernel table: every op registers ONE lowering — a pure JAX
function — and XLA owns fusion/placement. Pallas kernels are just lowerings
that call pallas_call.

Gradients: the reference requires a hand-written GradOpMaker per op
(grad_op_desc_maker.h:36). Here the default grad maker is *generic*: backward
rewrites insert a `grad:<type>` op whose lowering runs `jax.vjp` over the
forward lowering. XLA CSE merges the recomputed forward with the original, so
this costs nothing at runtime and removes ~500 hand-written grad kernels.
Ops can still register a manual_grad lowering when vjp is wrong (e.g.
straight-through estimators) or a custom grad maker for program-level rewrites.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence


@dataclasses.dataclass
class OpDef:
    type: str
    # lower(ctx, ins, attrs) -> outs.
    #   ins:  {slot_name: [jax arrays]}   outs: {slot_name: [jax arrays]}
    lower: Callable
    # Input slots that are not differentiable (indices, labels, masks...).
    nondiff_inputs: Sequence[str] = ()
    # Output slots that are not differentiable (argmax indices...).
    nondiff_outputs: Sequence[str] = ()
    # Uses ctx.rng (dropout, uniform_random...). Such ops get a deterministic
    # per-op PRNG key so the generic vjp grad sees the identical randomness.
    stateful: bool = False
    # Optional manual grad lowering: (ctx, ins, attrs) -> {input_slot: grads}
    # where ins additionally contains "<slot>@GRAD" entries for outputs.
    manual_grad: Optional[Callable] = None
    # If set, backward uses this to emit grad ops instead of the generic one:
    # f(op, grad_name_of: dict out_var->grad_var) -> (list[op_spec], dict in_var->grad_var)
    custom_grad_maker: Optional[Callable] = None
    # Marks ops that mutate persistable state (optimizer updates): their
    # outputs may alias inputs by var name (ParamOut == Param).
    inplace: bool = False
    # Semantic version, bumped on incompatible attr/behavior changes;
    # checked when loading saved programs/checkpoints (the reference's
    # op_compatible_info.h version gating).
    version: int = 1
    # Optional static shape rule for the analysis verifier
    # (paddle_tpu/analysis/shape_infer.py): fn(op, in_specs, block) ->
    # {out var name: ((shape with -1 dyn dims), dtype name)}. Only needed
    # for ops whose lowering cannot run under jax.eval_shape (control
    # flow over sub-blocks, host callbacks); pure lowerings get shape
    # inference for free.
    abstract_eval: Optional[Callable] = None


class OpRegistry:
    def __init__(self):
        self._ops: Dict[str, OpDef] = {}

    def register(self, opdef: OpDef):
        if opdef.type in self._ops:
            raise ValueError(f"op {opdef.type!r} already registered")
        self._ops[opdef.type] = opdef
        return opdef

    def get(self, op_type: str, where: Optional[str] = None) -> OpDef:
        """Look up an OpDef; `where` ("{block}/{op_idx}") names the
        originating program op when the lookup happens during lowering,
        so an unregistered-op failure points at the op, not just the
        type. Near-miss suggestions cover the typo case."""
        try:
            return self._ops[op_type]
        except KeyError:
            import difflib
            close = difflib.get_close_matches(
                op_type, list(self._ops), n=3, cutoff=0.6)
            hint = ("; did you mean " +
                    ", ".join(repr(c) for c in close) + "?") if close \
                else ""
            at = f" (at block/op {where})" if where else ""
            raise NotImplementedError(
                f"op {op_type!r} has no registered TPU lowering "
                f"({len(self._ops)} ops registered{hint}){at}"
            ) from None

    def has(self, op_type: str) -> bool:
        return op_type in self._ops

    def types(self):
        return sorted(self._ops)


REGISTRY = OpRegistry()


def register_op(op_type, *, nondiff_inputs=(), nondiff_outputs=(), stateful=False,
                manual_grad=None, custom_grad_maker=None, inplace=False,
                version=1, abstract_eval=None):
    """Decorator: @register_op("mul") def _mul(ctx, ins, attrs): ..."""

    def deco(fn):
        REGISTRY.register(OpDef(
            type=op_type, lower=fn,
            nondiff_inputs=tuple(nondiff_inputs),
            nondiff_outputs=tuple(nondiff_outputs),
            stateful=stateful, manual_grad=manual_grad,
            custom_grad_maker=custom_grad_maker, inplace=inplace,
            version=version, abstract_eval=abstract_eval))
        return fn

    return deco


def register_abstract_eval(op_type):
    """Attach a static shape rule to an already-registered op:

        @register_abstract_eval("while")
        def _while_specs(op, in_specs, block): ...

    Used by ops whose lowering cannot abstract-eval (control flow,
    host callbacks) so the analysis verifier can still propagate
    (shape, dtype) through them."""

    def deco(fn):
        REGISTRY.get(op_type).abstract_eval = fn
        return fn

    return deco


def simple_op(op_type, in_slots, out_slots, fn, **kw):
    """Register an op whose lowering is elementwise-style positional:
    fn(*arrays, **attrs) -> array or tuple of arrays."""

    def lower(ctx, ins, attrs):
        args = [ins[s][0] for s in in_slots]
        out = fn(*args, **attrs)
        if not isinstance(out, tuple):
            out = (out,)
        return {s: [o] for s, o in zip(out_slots, out)}

    REGISTRY.register(OpDef(type=op_type, lower=lower, **kw))
    return lower

"""Whole-block lowering: Program IR -> one JAX function -> one XLA computation.

Reference contrast: the fluid Executor interprets a block op-by-op with per-op
kernel dispatch and a device sync at the end (executor.cc:451-458). On TPU
that design throws away XLA fusion, so here the entire block becomes a single
traced JAX function; XLA owns scheduling, fusion, memory planning (its buffer
assignment subsumes the reference's eager-deletion GC passes,
ir/memory_optimize_pass/) and collective insertion. The architectural
precedent inside the reference itself is the nGraph subgraph engine
(ir/ngraph_subgraph_pass.cc:50 — compile a fused subgraph once, run many
times); we make it total instead of best-effort.

Also here:
- shape inference via jax.eval_shape over op lowerings (replaces ~500
  hand-written InferShape functions, operator.h:430);
- the generic vjp grad-op lowering used by backward.py (replaces per-op
  GradOpMakers, grad_op_desc_maker.h:36).
"""
from __future__ import annotations

import contextlib
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .dtypes import as_np_dtype, is_floating
from .registry import REGISTRY

GRAD_SUFFIX = "@GRAD"
# Placeholder for the dynamic (batch) dimension during build-time shape
# inference; outputs containing this dim are mapped back to -1. A large
# prime so it cannot collide with a real static layer width.
_DYN_DIM = 100003


class LowerCtx:
    """Per-trace context: PRNG derivation, train/infer mode, mesh info."""

    def __init__(self, base_key, is_test=False, mesh=None):
        self.base_key = base_key
        self.is_test = is_test
        self.mesh = mesh
        # True while lowering a sub-block inside lax.cond/while_loop —
        # ordered effects are not allowed there (see _nan_inf_guard)
        self.in_control_flow = False

    def rng_for(self, op_id: int):
        return jax.random.fold_in(self.base_key, np.uint32(op_id))


def _gather_slot(env, names):
    vals = []
    for n in names:
        if n == "":
            continue
        if n not in env:
            raise KeyError(f"var {n!r} not materialised before use")
        vals.append(env[n])
    return vals


def _nan_inf_guard(op, name, val, in_control_flow, op_idx):
    """FLAGS_check_nan_inf: host callback on every float op output
    (reference operator.cc:820-822 checks every output tensor when the
    flag is set). Top level uses an ordered io_callback that RAISES on
    Inf/Nan; inside lax.cond/while_loop sub-blocks ordered effects are
    rejected by JAX, so the guard degrades to jax.debug.callback, which
    reports loudly but cannot abort the run. On a trip the full
    provenance (op type, block/op index, offending output, input var
    names) goes into the monitor's flight recorder before the raise, so
    a post-mortem names the op even if the exception text is swallowed
    by a retry loop. Debug mode only."""
    from jax.experimental import io_callback

    op_type = op.type
    block_idx = op.block.idx if getattr(op, "block", None) is not None \
        else 0
    in_names = [n for ns in op.inputs.values() for n in ns if n]
    where = f"block {block_idx}/op {'?' if op_idx is None else op_idx}"
    msg = (f"Operator {op_type!r} at {where} output {name!r} contains "
           f"Inf/Nan; op inputs {in_names} (FLAGS_check_nan_inf)")

    def _trip(arr):
        a = np.asarray(arr)
        if np.isfinite(a).all():
            return False
        from ..monitor import STAT_ADD, flight_record
        STAT_ADD("executor.nan_inf_trips")
        flight_record(
            "nan_inf", op_type=op_type, block=block_idx,
            op=(-1 if op_idx is None else op_idx), output=name,
            inputs=in_names, shape=list(np.shape(a)),
            n_nonfinite=int(np.size(a) - np.isfinite(a).sum()))
        return True

    def cb(arr):
        if _trip(arr):
            raise FloatingPointError(msg)
        return np.zeros((), np.bool_)

    if in_control_flow:
        def report(arr):
            if _trip(arr):
                print(f"FLAGS_check_nan_inf: {msg} (inside control "
                      f"flow; run aborts are only possible at top "
                      f"level)")
        jax.debug.callback(report, val)
    else:
        io_callback(cb, jax.ShapeDtypeStruct((), np.bool_), val,
                    ordered=True)


def _op_scope(op, op_idx):
    """jax.named_scope('{op.type}:{block}/{op_idx}') around one op's
    emission (FLAGS_op_trace_scopes): the scope lands in the jaxpr name
    stack, so HLO op_name metadata, MLIR debug locations, and XPlane
    traces all attribute back to the Program op — the trace-side half
    of the reference's per-op RecordEvent (platform/profiler.cc). Ops
    lowered outside lower_block (shape inference) pass op_idx=None and
    stay unscoped.

    Ops the fusion-scope pass tagged (op._fusion_group, set at
    FLAGS_graph_opt_level=2 by analysis/passes/fusion.py) share a
    'ewfuseN/' scope prefix, so a whole elementwise chain lands under
    one name-stack entry — one fusion candidate for XLA instead of N
    disjoint scopes. The group scope is emitted even with trace scopes
    off: it exists for the compiler, not just the profiler."""
    from .flags import FLAGS
    if op_idx is None:
        return contextlib.nullcontext()
    group = getattr(op, "_fusion_group", None)
    if not FLAGS.op_trace_scopes:
        return (jax.named_scope(group) if group
                else contextlib.nullcontext())
    block_idx = op.block.idx if getattr(op, "block", None) is not None \
        else 0
    prefix = f"{group}/" if group else ""
    return jax.named_scope(f"{prefix}{op.type}:{block_idx}/{op_idx}")


def run_op(op, env, ctx, op_idx=None):
    """Execute one op's lowering against env (name -> array)."""
    from .flags import FLAGS
    blk = op.block.idx if getattr(op, "block", None) is not None else 0
    opdef = REGISTRY.get(
        op.type, where=f"{blk}/{'?' if op_idx is None else op_idx}")
    ins = {}
    for slot, names in op.inputs.items():
        vals = _gather_slot(env, names)
        if vals:
            ins[slot] = vals
    opctx = _OpCtx(ctx, op)
    # live view of already-materialised vars — lets keep-previous-value
    # semantics (conditional_block false branch) read carried state
    opctx.env = env
    with _op_scope(op, op_idx):
        try:
            outs = opdef.lower(opctx, ins, op.attrs)
        except Exception as e:
            # operator attribution on failures (reference op_call_stack.cc:
            # PADDLE_ENFORCE appends the Python-level op that emitted the
            # kernel): name the op, its input slots/shapes, and attrs so
            # users see WHICH Program op died, not just a jnp traceback
            shapes = {s: [getattr(v, "shape", "?") for v in vs]
                      for s, vs in ins.items()}
            note = (f"[operator {op.type!r}] inputs {shapes} -> outputs "
                    f"{dict(op.outputs)}, attrs {op.attrs}")
            if hasattr(e, "add_note"):  # PEP 678, Python >= 3.11
                e.add_note(note)
            else:
                e.__notes__ = [*getattr(e, "__notes__", []), note]
            raise
        check = FLAGS.check_nan_inf
        for slot, names in op.outputs.items():
            if slot not in outs:
                continue
            vals = outs[slot]
            for name, val in zip(names, vals):
                if name:
                    env[name] = val
                    if check and hasattr(val, "dtype") and \
                            is_floating(val.dtype):
                        _nan_inf_guard(op, name, val,
                                       ctx.in_control_flow, op_idx)


class _OpCtx:
    """View of LowerCtx bound to one op: gives it its deterministic key."""

    def __init__(self, ctx: LowerCtx, op):
        self._ctx = ctx
        self._op = op
        self.is_test = ctx.is_test or bool(op.attrs.get("is_test", False))
        self.mesh = ctx.mesh
        self.block = getattr(op, "block", None)
        self.attrs = op.attrs

    @property
    def rng(self):
        # Stateful ops fold the op's stable id so the generic vjp grad (which
        # re-lowers the fwd op under jax.vjp with the same id) sees identical
        # randomness — the dropout-mask-consistency problem the reference
        # solves by stashing the mask in an output var.
        fwd_id = self._op.attrs.get("fwd_id", self._op.id)
        return self._ctx.rng_for(fwd_id)

    def sub_block(self, idx):
        return self._op.block.program.blocks[idx]

    def lower_sub_block(self, block, env):
        prev = self._ctx.in_control_flow
        self._ctx.in_control_flow = True
        try:
            for i, op in enumerate(block.ops):
                run_op(op, env, self._ctx, op_idx=i)
        finally:
            self._ctx.in_control_flow = prev
        return env


def lower_block(block, env: Dict, ctx: LowerCtx):
    for i, op in enumerate(block.ops):
        run_op(op, env, ctx, op_idx=i)
    return env


# ---------------------------------------------------------------------------
# Build-time shape inference
# ---------------------------------------------------------------------------

def infer_op_shapes(op, block):
    """Fill in output var shapes/dtypes by abstract-evaluating the lowering."""
    opdef = REGISTRY.get(op.type)

    env = {}
    for slot, names in op.inputs.items():
        for n in names:
            if not n or n in env:
                continue
            v = block.var(n)
            if v.shape is None:
                return  # cannot infer yet
            shape = tuple(_DYN_DIM if d == -1 else d for d in v.shape)
            env[n] = jax.ShapeDtypeStruct(shape, as_np_dtype(v.dtype))

    def f(e):
        e = dict(e)
        ctx = LowerCtx(jax.random.PRNGKey(0))
        run_op(op, e, ctx)
        return {n: e[n] for n in op.output_names() if n and n in e}

    out = jax.eval_shape(f, env)
    for name, sds in out.items():
        v = block.var(name)
        v.shape = tuple(-1 if d == _DYN_DIM else int(d) for d in sds.shape)
        v.dtype = jnp.dtype(sds.dtype).name if sds.dtype != jnp.bfloat16 \
            else "bfloat16"


# ---------------------------------------------------------------------------
# Generic grad op: grad::<type> — vjp over the forward lowering
# ---------------------------------------------------------------------------

def _is_diff(arr):
    return is_floating(arr.dtype)


def generic_grad_lower(ctx, ins, attrs):
    fwd_type = attrs["fwd_type"]
    fwd_attrs = attrs["fwd_attrs"]
    fwd_in_slots: Dict[str, int] = attrs["fwd_in_slots"]    # slot -> arity
    fwd_out_slots: List[str] = attrs["fwd_out_slots"]
    # Which positions of each output slot have an incoming cotangent;
    # _gather_slot drops empty-name entries, so this mask restores
    # positional alignment for multi-output slots (e.g. split).
    grad_mask: Dict[str, List[bool]] = attrs.get("fwd_out_grad_mask", {})
    opdef = REGISTRY.get(fwd_type)

    # Split inputs into forward-inputs vs incoming output-cotangents.
    fwd_ins = {s: ins[s] for s in fwd_in_slots if s in ins}
    fake_op = _FakeOp(fwd_type, fwd_attrs, attrs["fwd_id"], ctx)

    if opdef.manual_grad is not None:
        # positionally realign multi-output cotangent lists: _gather_slot
        # drops empty-name entries, so without the mask a manual grad
        # would zip Outputs@GRAD[0] against Ids[1] etc. Missing
        # cotangents become None — manual grads must skip them.
        ins2 = dict(ins)
        for slot in fwd_out_slots:
            gslot = slot + GRAD_SUFFIX
            mask = grad_mask.get(slot)
            if gslot in ins2 and mask is not None and \
                    sum(mask) == len(ins2[gslot]) and \
                    len(mask) != len(ins2[gslot]):
                avail = list(ins2[gslot])
                ins2[gslot] = [avail.pop(0) if present else None
                               for present in mask]
        return opdef.manual_grad(_OpCtx(ctx._ctx, fake_op), ins2,
                                 fwd_attrs)

    diff_slots = [s for s in fwd_ins
                  if s not in opdef.nondiff_inputs
                  and all(_is_diff(a) for a in fwd_ins[s])]
    nondiff = {s: fwd_ins[s] for s in fwd_ins if s not in diff_slots}

    def f(diff):
        full = dict(nondiff)
        full.update(diff)
        outs = opdef.lower(_OpCtx(ctx._ctx, fake_op), full, fwd_attrs)
        return {s: outs[s] for s in fwd_out_slots if s in outs}

    diff_in = {s: fwd_ins[s] for s in diff_slots}
    primal_out, vjp = jax.vjp(f, diff_in)

    cots = {}
    for slot, prims in primal_out.items():
        gslot = slot + GRAD_SUFFIX
        avail = list(ins.get(gslot, []))
        mask = grad_mask.get(slot, [bool(avail)] * len(prims))
        slot_cots = []
        for a, present in zip(prims, mask):
            if present and avail and _is_diff(a):
                slot_cots.append(avail.pop(0).astype(a.dtype))
            else:
                slot_cots.append(jnp.zeros(a.shape, a.dtype))
        cots[slot] = slot_cots
    (gin,) = vjp(cots)
    return {s + GRAD_SUFFIX: gin[s] for s in gin}


class _FakeOp:
    """Stand-in op object so _OpCtx can derive the forward op's PRNG key."""

    def __init__(self, type_, attrs, fwd_id, octx):
        self.type = type_
        self.attrs = dict(attrs)
        self.attrs["fwd_id"] = fwd_id
        self.id = fwd_id
        self.block = octx.block


from .registry import OpDef  # noqa: E402

REGISTRY.register(OpDef(type="grad::generic", lower=generic_grad_lower))

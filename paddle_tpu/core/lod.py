"""LoDTensor: host-side tensor wrapper with level-of-detail metadata.

Reference: framework::LoDTensor (lod_tensor.h:104) — a dense buffer plus
`LoD = vector<vector<size_t>>` ragged-sequence offsets. On TPU the device
representation is always dense (XLA static shapes); LoD lives host-side and
sequence ops take (padded, lengths) pairs (ops/sequence_ops.py). This class
preserves the user-facing API: set_lod/lod/recursive_sequence_lengths.
"""
from __future__ import annotations

import numpy as np


class LoDTensor:
    def __init__(self, data=None, lod=None):
        self._data = np.asarray(data) if data is not None else None
        self._lod = [list(level) for level in (lod or [])]

    # -- fluid API -------------------------------------------------------
    def set(self, data, place=None):
        self._data = np.asarray(data)

    def set_lod(self, lod):
        self._lod = [list(level) for level in lod]

    def lod(self):
        return self._lod

    def set_recursive_sequence_lengths(self, lengths):
        self._lod = []
        for level in lengths:
            offsets = [0]
            for n in level:
                offsets.append(offsets[-1] + n)
            self._lod.append(offsets)

    def recursive_sequence_lengths(self):
        out = []
        for level in self._lod:
            out.append([level[i + 1] - level[i]
                        for i in range(len(level) - 1)])
        return out

    def has_valid_recursive_sequence_lengths(self):
        for level in self._lod:
            if any(level[i] > level[i + 1] for i in range(len(level) - 1)):
                return False
        return True

    def shape(self):
        return list(self._data.shape)

    def numpy_value(self):
        return self._data

    def __array__(self, dtype=None):
        return self._data if dtype is None else self._data.astype(dtype)

    # Pack ragged rows into (padded, lengths) for sequence ops.
    def to_padded(self, pad_value=0.0, multiple=1):
        """multiple > 1 rounds the pad target up (e.g. to 8): sequence
        ops mask by lengths so extra padding is correctness-neutral, and
        bucketing keeps per-shape executable-cache churn bounded for
        ragged batches whose max length varies step to step."""
        if not self._lod:
            return self._data, None
        level = self._lod[-1]
        lengths = np.asarray([level[i + 1] - level[i]
                              for i in range(len(level) - 1)])
        maxlen = int(lengths.max()) if len(lengths) else 0
        if multiple > 1 and maxlen % multiple:
            maxlen += multiple - maxlen % multiple
        feat = self._data.shape[1:]
        out = np.full((len(lengths), maxlen) + feat, pad_value,
                      self._data.dtype)
        for i in range(len(lengths)):
            out[i, :lengths[i]] = self._data[level[i]:level[i + 1]]
        return out, lengths

    @staticmethod
    def from_ragged(rows, dtype="float32"):
        data = np.concatenate([np.asarray(r, dtype) for r in rows], axis=0)
        t = LoDTensor(data)
        t.set_recursive_sequence_lengths([[len(r) for r in rows]])
        return t


class LoDTensorArray(list):
    """reference: LoDTensorArray = vector<LoDTensor>."""
    pass

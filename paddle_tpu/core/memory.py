"""Memory accounting: device (HBM) + host scope usage.

Reference: memory/allocation/allocator_facade.cc owns GPU memory with
fraction caps (FLAGS_fraction_of_gpu_memory_to_use) and the
scope-memory monitor (details/scope_buffered_monitor.cc) tracks
per-scope tensor bytes. On TPU, XLA buffer assignment owns device
memory — this module SURFACES it (PJRT memory_stats) instead of
managing it, and adds the scope-bytes monitor the round-2 review
flagged as missing.
"""
from __future__ import annotations

from typing import Dict, Optional

__all__ = ["device_memory_stats", "scope_memory_stats",
           "assert_hbm_within", "record_device_memory"]


def device_memory_stats(device=None) -> Dict[str, int]:
    """PJRT allocator stats for one device: bytes_in_use,
    peak_bytes_in_use, bytes_limit (keys present when the backend
    reports them; CPU backends may return {})."""
    import jax
    dev = device or jax.devices()[0]
    stats = getattr(dev, "memory_stats", None)
    if stats is None:
        return {}
    try:
        return dict(stats() or {})
    except Exception:
        return {}


def scope_memory_stats(scope=None) -> Dict[str, int]:
    """Bytes held by a Scope, split host (numpy) vs device (jax.Array);
    the scope_buffered_monitor.cc analogue."""
    import numpy as np
    import jax
    from .scope import global_scope
    scope = scope or global_scope()
    host = dev = count = 0
    for name in scope.names():
        v = scope.find_var(name)  # None for declared-but-unset vars
        if v is None:
            continue
        count += 1
        nbytes = int(getattr(v, "nbytes", 0) or 0)
        if isinstance(v, jax.Array) and not isinstance(v, np.ndarray):
            dev += nbytes
        else:
            host += nbytes
    return {"vars": count, "host_bytes": host, "device_bytes": dev,
            "total_bytes": host + dev}


def record_device_memory(device=None) -> Dict[str, int]:
    """Sample PJRT allocator stats into the monitor as gauges
    (memory.device_bytes_in_use / peak / limit). The executor calls
    this once per step when FLAGS_enable_monitor is set, giving the
    live-HBM-per-step series the reference's scope_buffered_monitor
    derives from per-scope tensor bytes. No-op when the monitor is
    disabled or the backend reports no stats (CPU)."""
    from ..monitor import STAT_SET, enabled
    if not enabled():
        return {}
    s = device_memory_stats(device)
    for key, stat in (("bytes_in_use", "memory.device_bytes_in_use"),
                      ("peak_bytes_in_use", "memory.device_peak_bytes"),
                      ("bytes_limit", "memory.device_bytes_limit")):
        if key in s:
            STAT_SET(stat, s[key])
    return s


def assert_hbm_within(fraction: float, device=None) -> Optional[float]:
    """Guard: raise if bytes_in_use exceeds `fraction` of the device
    limit (the TPU reading of FLAGS_fraction_of_gpu_memory_to_use as a
    *check* rather than a reservation). Returns the current fraction,
    or None when the backend reports no stats."""
    s = device_memory_stats(device)
    used = s.get("bytes_in_use")
    limit = s.get("bytes_limit")
    if not used or not limit:
        return None
    frac = used / limit
    if frac > fraction:
        raise MemoryError(
            f"HBM usage {used / 2**30:.2f} GiB is "
            f"{frac:.1%} of the {limit / 2**30:.2f} GiB limit "
            f"(> allowed {fraction:.1%})")
    return frac

"""Continuous-batching generation: slot-based KV-cache decode serving.

Reference: the reference framework ships autoregressive inference as
while_op beam-search decoders inside the graph — one request per
invocation. Serving LLM traffic needs the Orca model instead:
iteration-level scheduling, where the scheduler re-decides the batch
composition BETWEEN decode steps, so a finished request's slot is handed
to a queued request immediately rather than waiting for the whole batch
to finish.

On TPU the constraint that shapes this design is XLA shape
specialization: the decode step must be ONE fixed-shape executable for
the engine's whole lifetime. `models/gpt.py:build_decode_step` therefore
carries a per-slot `decode_pos` vector plus `slot_reset`/`slot_active`
feeds: a new request joins a running batch by feeding reset=1 on its
slot (the graph zeroes that slot's K/V rows in-device — no host zero
upload, no recompile), and an empty slot rides along muted with
active=0. Admission, prefill (prompt tokens stepped through the same
graph), sampling (host-side, models/sampling.py), eviction and
re-admission all happen without ever presenting XLA a novel shape —
`Executor.cache_stats()` misses stay frozen after the single warmup
compile, the same zero-post-warmup-compile contract `ServingEngine`
keeps for encoder traffic.

Queueing reuses the `batcher.py` vocabulary: bounded queue with
`QueueFullError` backpressure, per-request deadlines failing with
`DeadlineExceededError`, `EngineClosedError` + drain semantics on
shutdown, `_Response` future handles.

Paged KV (FLAGS_gen_paged_kv, the default): instead of one contiguous
`[max_slots, max_seq]` slab per layer, K/V lives in per-layer physical
POOLS of fixed-size blocks (`serving/kv_blocks.py`), addressed through
per-slot block tables fed to the `paged_attention` op every step. Peak
KV HBM becomes `num_blocks x block_bytes` — budget-derived and
decoupled from the longest POSSIBLE sequence — and three scheduler
moves fall out of the indirection: admission gates on free BLOCKS
(actual tokens) rather than slots alone; a slot "reset" is just
releasing its blocks back to the pool (no in-graph wipe — the table
simply never maps the old blocks again); and shared prompt prefixes
hit a content-hash `PrefixCache` so identical system prompts reuse the
same physical blocks and skip re-prefill. Long prompts retire through
a second fixed-shape executable that prefills a whole block per step
(chunked prefill), so a 10k-token prompt costs ~10k/block_size
iterations interleaved with — never stalling — the decode batch. The
compile contract widens from one executable to exactly two (decode +
chunk prefill), both compiled in `start()`: `post_warmup_compiles()`
stays 0 for the engine's lifetime either way.

Speculative decoding (FLAGS_gen_spec_decode / GenerationRequest
.spec_decode, paged engines only): a host-side n-gram drafter
(`serving/spec_decode.py`) proposes up to FLAGS_spec_decode_k tokens per
slot between steps, and a THIRD fixed-shape executable — the
`[max_slots, k+1]` batched verify step (`models/gpt.py:
build_spec_verify_step`) — scores every draft position in one pass.
`models/sampling.py:accept_draft` commits the longest agreeing prefix
through the same sample_token path as serial decode, so outputs stay
token-for-token identical at any temperature; each accepted token skips
one whole decode iteration. The verify executable is compiled in
`start()` alongside the other two, keeping `post_warmup_compiles()` at
0.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from .. import goodput as _goodput
from .. import trace
from ..monitor import STAT_ADD, STAT_OBSERVE, STAT_SET
from ..monitor import enabled as _monitor_on
from ..resilience.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from ..resilience.faults import TransientFault
from ..resilience.faults import injector as _fault_injector
from ..resilience.retry import RetryPolicy, is_transient
from .batcher import (DeadlineExceededError, EngineClosedError,
                      FRACTION_BUCKETS, MS_BUCKETS, OverloadedError,
                      QueueFullError, ServingError, _Response)
from .kv_blocks import (SCRATCH_BLOCK, BlockPool, PrefixCache,
                        blocks_for_tokens)

__all__ = ["GenerationRequest", "SlotManager", "GenerationEngine"]

# Effective tokens committed per verify step: 1 (full reject) through
# spec_k + 1 (full accept + bonus token). Count-valued, so the ms/
# fraction bucket ladders don't fit; upper rungs leave headroom for
# larger FLAGS_spec_decode_k settings.
SPEC_TOKEN_BUCKETS = (1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 12.0, 16.0)


class GenerationRequest:
    """One generation job: prompt in, up to `max_new_tokens` out.

    `temperature`/`top_k` select the sampling policy (see
    models/sampling.py; temperature 0 = greedy, fully deterministic
    given `seed`). `eos_id` stops the request early when sampled.
    `timeout_ms` is a wall-clock deadline covering queue wait AND
    decode; None falls back to the engine default. `stream_cb(token_id)`
    fires from the engine thread after every generated token — the
    streaming hook (and the loadgen's TTFT/inter-token probe).
    `spec_decode` opts this request in/out of speculative decoding
    (serving/spec_decode.py): None defers to the engine default
    (FLAGS_gen_spec_decode), False forces plain one-token decode, True
    speculates when the engine carries the verify executable (and
    degrades silently to plain decode when it does not — outputs are
    identical either way, only the step count changes).
    """

    __slots__ = ("prompt", "max_new_tokens", "temperature", "top_k",
                 "eos_id", "timeout_ms", "seed", "stream_cb",
                 "spec_decode")

    def __init__(self, prompt: Sequence[int], max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None,
                 timeout_ms: Optional[float] = None, seed: int = 0,
                 stream_cb: Optional[Callable[[int], None]] = None,
                 spec_decode: Optional[bool] = None):
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError("GenerationRequest: prompt must be "
                             "non-empty")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("GenerationRequest: max_new_tokens must "
                             "be >= 1")
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.timeout_ms = timeout_ms
        self.seed = int(seed)
        self.stream_cb = stream_cb
        self.spec_decode = None if spec_decode is None \
            else bool(spec_decode)


class SlotManager:
    """Free-list over the decode graph's B slots.

    Owned by the engine worker thread (admission and eviction both
    happen between steps on that thread), so no internal locking.
    """

    def __init__(self, n_slots: int):
        if n_slots < 1:
            raise ValueError("SlotManager: need at least one slot")
        self.n_slots = int(n_slots)
        self._free = list(range(self.n_slots - 1, -1, -1))  # pop() -> 0 first

    def acquire(self) -> Optional[int]:
        """Lowest free slot index, or None when fully occupied."""
        return self._free.pop() if self._free else None

    def release(self, slot: int):
        if slot in self._free or not 0 <= slot < self.n_slots:
            raise ValueError(f"SlotManager: bad release of slot {slot}")
        self._free.append(slot)
        self._free.sort(reverse=True)

    def free_count(self) -> int:
        return len(self._free)

    def active_count(self) -> int:
        return self.n_slots - len(self._free)


class _SlotState:
    """Per-occupied-slot decode progress (worker-thread private)."""

    __slots__ = ("req", "response", "fed", "cur", "generated", "rng",
                 "needs_reset", "deadline", "t_submit", "t_prev_token",
                 "ttft_ms", "blocks", "n_cached", "registered",
                 "span", "phase_span", "fetch_s",
                 "spec_k_cur", "spec_acc_ewma")

    def __init__(self, req: GenerationRequest, response: _Response,
                 deadline: Optional[float], t_submit: float):
        self.req = req
        self.response = response
        self.fed = 0                  # tokens already stepped (== the
        #                               slot's next KV write position)
        self.cur = req.prompt[0]      # next token to feed
        self.generated: List[int] = []
        self.rng = np.random.RandomState(req.seed)
        self.needs_reset = True       # feed slot_reset=1 on first step
        self.deadline = deadline
        self.t_submit = t_submit
        self.t_prev_token: Optional[float] = None
        self.ttft_ms: Optional[float] = None
        # paged-KV bookkeeping: the slot's block table (shared prefix
        # blocks first, then owned), prefix-cache hit length in tokens,
        # and whether the full prompt blocks have been registered
        self.blocks: List[int] = []
        self.n_cached = 0
        self.registered = False
        # Tracing: the request span (carried over from _Queued — spans
        # cross the submit -> worker thread hand-off ON these objects),
        # the current lifecycle phase span (prefill, then decode), and
        # accumulated fetch-block seconds from the steps this slot rode.
        self.span = None
        self.phase_span = None
        self.fetch_s = 0.0
        # adaptive speculative decoding: per-slot draft budget and
        # acceptance-rate EWMA (None until the first measured ratio)
        self.spec_k_cur: Optional[int] = None
        self.spec_acc_ewma: Optional[float] = None


class _Queued:
    __slots__ = ("req", "response", "deadline", "t_submit",
                 "span", "qspan")

    def __init__(self, req, response, deadline, t_submit):
        self.req = req
        self.response = response
        self.deadline = deadline
        self.t_submit = t_submit
        self.span = None   # request span (hand-off to the worker)
        self.qspan = None  # its queue-wait child


class GenerationEngine:
    """Iteration-level (continuous-batching) generation service.

    Construct with a trained `scope` (weights under the training-graph
    names) and the model's TransformerConfig; the engine builds its own
    `max_slots`-wide decode program whose STATE names carry
    `state_prefix`, so it can share the scope with training graphs or a
    serial batch=1 decode graph without collision. Lifecycle mirrors
    `ServingEngine`: `start()` (state init + one warmup step = the one
    compile of the engine's lifetime), `submit`/`generate` from any
    thread, `stop(drain=True)`.
    """

    def __init__(self, cfg, scope, exe=None,
                 max_slots: Optional[int] = None,
                 max_seq: Optional[int] = None,
                 queue_capacity: Optional[int] = None,
                 default_timeout_ms: Optional[float] = None,
                 state_prefix: str = "gen.",
                 paged: Optional[bool] = None,
                 block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 spec_decode: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 spec_adaptive: Optional[bool] = None):
        import paddle_tpu as fluid
        from ..core.flags import FLAGS
        from ..models import gpt

        self.cfg = cfg
        self.scope = scope
        self.exe = exe if exe is not None else fluid.Executor()
        self.max_slots = int(max_slots if max_slots is not None
                             else FLAGS.serving_max_batch_size)
        self.max_seq = int(max_seq if max_seq is not None
                           else cfg.max_seq_len)
        self.queue_capacity = int(queue_capacity
                                  if queue_capacity is not None
                                  else FLAGS.serving_queue_capacity)
        self.default_timeout_ms = (
            default_timeout_ms if default_timeout_ms is not None
            else FLAGS.serving_default_timeout_ms)
        self.paged = bool(FLAGS.gen_paged_kv if paged is None else paged)
        # the decode-step program(s); their startup is never run (it
        # would re-init the shared trained weights) — state is seeded
        # by _ensure_decode_state in start()
        self._prog = fluid.Program()
        self._startup = fluid.Program()
        self._prefill_prog = None
        self._pool: Optional[BlockPool] = None
        self._prefix: Optional[PrefixCache] = None
        if self.paged:
            self.block_size = int(
                min(block_size if block_size is not None
                    else FLAGS.gen_kv_block_size, self.max_seq))
            self.num_blocks = self._resolve_pool_blocks(kv_pool_blocks)
            with fluid.program_guard(self._prog, self._startup):
                self.step = gpt.build_paged_decode_step(
                    cfg, batch=self.max_slots, max_seq=self.max_seq,
                    block_size=self.block_size,
                    num_blocks=self.num_blocks, seq_tokens=1,
                    state_prefix=state_prefix)
            # the second (and last) executable of the lifetime: retires
            # one whole block of prompt per row per step
            self._prefill_prog = fluid.Program()
            self._prefill_startup = fluid.Program()
            with fluid.program_guard(self._prefill_prog,
                                     self._prefill_startup):
                self.prefill_step = gpt.build_paged_decode_step(
                    cfg, batch=self.max_slots, max_seq=self.max_seq,
                    block_size=self.block_size,
                    num_blocks=self.num_blocks,
                    seq_tokens=self.block_size,
                    state_prefix=state_prefix, with_logits=False)
            self._pool = BlockPool(self.num_blocks, self.block_size)
            self._prefix = PrefixCache(self._pool)
        else:
            spec_decode = False  # the slab graph has no verify substrate
            self.block_size = 0
            self.num_blocks = 0
            with fluid.program_guard(self._prog, self._startup):
                self.step = gpt.build_decode_step(
                    cfg, batch=self.max_slots, max_seq=self.max_seq,
                    state_prefix=state_prefix)
        # speculative decoding (serving/spec_decode.py): paged-only —
        # the verify step is the THIRD and last fixed-shape executable,
        # sharing the decode/prefill programs' K/V pools via
        # state_prefix. Engines with spec off build nothing extra and
        # keep the two-executable warmup unchanged.
        self.spec_decode = bool(FLAGS.gen_spec_decode
                                if spec_decode is None else spec_decode)
        self.spec_k = int(spec_k if spec_k is not None
                          else FLAGS.spec_decode_k)
        self._spec_prog = None
        self.spec_step = None
        self._drafter = None
        if self.spec_decode and self.spec_k >= 1:
            from .spec_decode import NgramDrafter
            self._spec_prog = fluid.Program()
            self._spec_startup = fluid.Program()
            with fluid.program_guard(self._spec_prog,
                                     self._spec_startup):
                self.spec_step = gpt.build_spec_verify_step(
                    cfg, batch=self.max_slots, max_seq=self.max_seq,
                    block_size=self.block_size,
                    num_blocks=self.num_blocks, k=self.spec_k,
                    state_prefix=state_prefix)
            self._drafter = NgramDrafter(
                max_ngram=int(FLAGS.spec_decode_ngram), k=self.spec_k)
        else:
            self.spec_decode = False
        # acceptance-aware adaptive draft length: host-side only (the
        # verify executable is still [max_slots, spec_k+1]); a slot
        # whose measured acceptance stops paying for the verify premium
        # shrinks its own proposal budget toward 1
        self.spec_adaptive = bool(
            FLAGS.spec_decode_adaptive if spec_adaptive is None
            else spec_adaptive) and self.spec_decode
        self._slots = SlotManager(self.max_slots)
        self._state: List[Optional[_SlotState]] = \
            [None] * self.max_slots
        # serializes paged KV structures (BlockPool / PrefixCache / the
        # pool arrays themselves) between the worker's iteration and
        # cross-process export/adopt (serving/disagg.py)
        self._kv_mutex = threading.Lock()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[_Queued] = []
        self._closed = False
        self._draining = True
        self._worker: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._warm_misses: Optional[int] = None
        # resilience: a failed decode step fails the requests that were
        # mid-step (their KV state is unreplayable) but never the
        # worker; repeated failures trip the breaker and submissions
        # shed with OverloadedError
        self._breaker = CircuitBreaker(name="generation")
        self._step_retry = RetryPolicy(
            is_retryable=lambda e: isinstance(e, TransientFault))
        self._engine_state = "warming"  # warming -> ready -> stopped

    # -- paged-pool sizing ----------------------------------------------
    def kv_block_bytes(self) -> int:
        """HBM bytes one block occupies across every layer's K+V pool
        (float32 today; the int8 KV leg only changes this number)."""
        if not self.paged:
            return 0
        return 2 * self.cfg.n_layers * self.block_size * \
            self.cfg.d_model * 4

    def kv_pool_bytes(self) -> int:
        """Total K/V pool HBM across layers — what the static memory
        planner prices for the paged program (pool persistables)."""
        if not self.paged:
            return 2 * self.cfg.n_layers * self.max_slots * \
                self.max_seq * self.cfg.d_model * 4
        return self.num_blocks * self.kv_block_bytes()

    def _resolve_pool_blocks(self, kv_pool_blocks) -> int:
        """Pool size precedence: ctor arg > FLAGS_gen_kv_pool_blocks >
        FLAGS_gen_kv_pool_bytes (budget // block_bytes) > full capacity
        (every slot can hold max_seq — no eviction pressure, but also
        no savings; production sets the budget)."""
        from ..core.flags import FLAGS
        per_slot = blocks_for_tokens(self.max_seq, self.block_size)
        if kv_pool_blocks is not None:
            # an explicit ctor arg is honored exactly (tests build
            # deliberately tight pools; submit reports requests that
            # can never fit) — only the BlockPool minimum applies
            return max(int(kv_pool_blocks), 2)
        if FLAGS.gen_kv_pool_blocks > 0:
            n = int(FLAGS.gen_kv_pool_blocks)
        elif FLAGS.gen_kv_pool_bytes > 0:
            block_bytes = 2 * self.cfg.n_layers * self.block_size * \
                self.cfg.d_model * 4
            n = int(FLAGS.gen_kv_pool_bytes) // block_bytes
        else:
            n = self.max_slots * per_slot + 1
        # floor: scratch + one slot's worth, or nothing ever admits
        return max(n, per_slot + 1)

    # -- lifecycle -------------------------------------------------------
    def init_scope(self):
        """Run the decode program's startup to give the scope FRESH
        random weights. Only for scratch scopes (loadgen, smoke tests):
        on a scope holding trained parameters this would wipe them —
        trained deployments skip this and let `start()` seed just the
        decode state."""
        self.exe.run(self._startup, scope=self.scope)
        return self

    def start(self):
        """Seed the decode state, run one warmup step per executable
        (slab: one; paged: decode + chunk prefill — ALL the compiles of
        the engine's lifetime, slots muted), then start the worker
        thread."""
        if self._worker is not None:
            return self
        from ..models import gpt
        blk = self._prog.global_block()
        gpt._ensure_decode_state(self.scope, blk, self.step.cache_names)
        if self.paged:
            B = self.max_slots
            mb = self.step.max_blocks_per_slot
            self._run_paged(self._prog, self.step,
                            np.zeros((B, 1), np.int64),
                            np.zeros((B, mb), np.int64),
                            np.zeros(B, np.int64),
                            np.zeros(B, np.int64))
            self._run_paged(self._prefill_prog, self.prefill_step,
                            np.zeros((B, self.block_size), np.int64),
                            np.zeros((B, mb), np.int64),
                            np.zeros(B, np.int64),
                            np.zeros(B, np.int64))
            if self.spec_step is not None:
                # the verify executable's one compile of the lifetime
                self._run_paged(self._spec_prog, self.spec_step,
                                np.zeros((B, self.spec_k + 1),
                                         np.int64),
                                np.zeros((B, mb), np.int64),
                                np.zeros(B, np.int64),
                                np.zeros(B, np.int64))
            STAT_SET("serving.gen_kv_blocks_total",
                     self._pool.capacity())
            STAT_SET("serving.gen_kv_blocks_free",
                     self._pool.free_count())
        else:
            self._run_step(np.zeros((self.max_slots, 1), np.int64),
                           reset=np.ones(self.max_slots, np.float32),
                           active=np.zeros(self.max_slots, np.float32))
        self._warm_misses = self.cache_stats()["misses"]
        self._closed = False
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="ptn-generation-worker",
                                        daemon=True)
        self._worker.start()
        self._engine_state = "ready"
        self._ready.set()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0):
        """Reject new submissions; drain=True finishes queued + active
        requests first, drain=False fails them with EngineClosedError."""
        self._ready.clear()
        self._engine_state = "stopped"
        with self._cond:
            self._closed = True
            self._draining = drain
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout)
            self._worker = None

    @property
    def ready(self) -> bool:
        return self._ready.is_set()

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    def health(self) -> dict:
        """Same shape as ServingEngine.health(): state warming / ready
        / degraded / open / stopped + breaker detail (for /healthz)."""
        if self._engine_state != "ready":
            return {"state": self._engine_state,
                    "breaker": self._breaker.state, "retry_after_s": 0.0}
        b = self._breaker.state
        state = {OPEN: "open", HALF_OPEN: "degraded",
                 CLOSED: "ready"}[b]
        return {"state": state, "breaker": b,
                "retry_after_s": self._breaker.retry_after_s()}

    def load(self) -> int:
        """Queued + active requests — what the router's least-loaded
        dispatch compares (the serving.gen_queue_depth /
        gen_active_slots gauges, read directly)."""
        with self._cond:
            queued = len(self._queue)
        return queued + self._slots.active_count()

    def cache_stats(self):
        """The executor's per-instance executable-cache counters; after
        `start()` the `misses` count must never move again — the
        zero-post-warmup-compile acceptance check
        (tools/serving_loadgen.py --generate --check-compiles)."""
        return self.exe.cache_stats()

    def kv_block_stats(self) -> dict:
        """Snapshot of the paged pool for reporting (loadgen records,
        sweep ledgers): capacity/free in blocks, the bytes the pool
        pins, and how many prefix-cache entries are resident."""
        if not self.paged:
            return {"paged": False, "pool_bytes": self.kv_pool_bytes()}
        return {"paged": True,
                "block_size": self.block_size,
                "blocks_total": self._pool.capacity(),
                "blocks_free": self._pool.free_count(),
                "prefix_entries": len(self._prefix),
                "pool_bytes": self.kv_pool_bytes()}

    def post_warmup_compiles(self) -> int:
        if self._warm_misses is None:
            return 0
        return self.cache_stats()["misses"] - self._warm_misses

    # -- request path ----------------------------------------------------
    def submit(self, req: GenerationRequest) -> _Response:
        """Enqueue; returns a future handle whose `.result()` blocks for
        ``{"tokens", "finish_reason", "ttft_ms", "e2e_ms"}``."""
        need = len(req.prompt) + req.max_new_tokens - 1
        if self.paged:
            # block-aware admission: a request that can never fit is
            # rejected here; one that merely has to WAIT for blocks
            # queues and is admitted by the worker when the pool drains
            need_blocks = blocks_for_tokens(need, self.block_size)
            if need_blocks > self.step.max_blocks_per_slot:
                raise ValueError(
                    f"request needs {need_blocks} KV blocks but a "
                    f"slot's block table holds at most "
                    f"{self.step.max_blocks_per_slot} "
                    f"(max_seq={self.max_seq}, "
                    f"block_size={self.block_size})")
            if need_blocks > self._pool.capacity():
                raise ValueError(
                    f"request needs {need_blocks} KV blocks but the "
                    f"engine's pool has only {self._pool.capacity()} "
                    f"allocatable blocks "
                    f"({self._pool.free_count()} free now)")
        elif need > self.max_seq:
            raise ValueError(
                f"request needs {need} cache positions but the engine "
                f"was built with max_seq={self.max_seq}")
        timeout_ms = req.timeout_ms if req.timeout_ms is not None \
            else self.default_timeout_ms
        now = time.perf_counter()
        deadline = now + timeout_ms / 1e3 if timeout_ms else None
        if not self._breaker.allow():
            raise OverloadedError(
                "generation backend is unhealthy (circuit breaker "
                "open)", retry_after_s=self._breaker.retry_after_s())
        resp = _Response()
        q = _Queued(req, resp, deadline, now)
        if trace.enabled():
            # Child of the caller's span (http.request, loadgen's
            # per-request root) when one is current, else a new root.
            q.span = trace.start_span(
                "gen.request",
                attrs={"prompt_tokens": len(req.prompt),
                       "max_new_tokens": req.max_new_tokens})
            resp.span = q.span
            q.qspan = trace.start_span("queue", parent=q.span)
        try:
            with self._cond:
                if self._closed:
                    raise EngineClosedError(
                        "generation engine is shut down")
                if len(self._queue) >= self.queue_capacity:
                    STAT_ADD("serving.gen_rejected")
                    raise QueueFullError(
                        f"generation queue at capacity "
                        f"({len(self._queue)}/{self.queue_capacity})")
                self._queue.append(q)
                STAT_ADD("serving.gen_requests")
                STAT_SET("serving.gen_queue_depth", len(self._queue))
                self._cond.notify_all()
        except ServingError as e:
            # Rejected before any worker saw it: the raise is the
            # completion (errored -> the tail rules keep the trace).
            trace.end_span(q.qspan, error=type(e).__name__)
            trace.complete_request(q.span,
                                   error=f"{type(e).__name__}: {e}")
            raise
        return resp

    def generate(self, prompt: Sequence[int], max_new_tokens: int,
                 **kw) -> dict:
        """Blocking submit+wait convenience."""
        return self.submit(GenerationRequest(
            prompt, max_new_tokens, **kw)).result()

    # -- decode step -----------------------------------------------------
    def _run_step(self, tokens, reset, active):
        out, = self.exe.run(
            self._prog,
            feed={self.step.token_var.name: tokens,
                  self.step.reset_var.name: reset,
                  self.step.active_var.name: active},
            fetch_list=[self.step.logits_var],
            scope=self.scope)
        return np.asarray(out)

    def _run_paged(self, prog, step, tokens, table, start, nvalid):
        out, = self.exe.run(
            prog,
            feed={step.token_var.name: tokens,
                  step.table_var.name: table,
                  step.start_var.name: start,
                  step.nvalid_var.name: nvalid},
            fetch_list=[step.logits_var],
            scope=self.scope)
        return np.asarray(out)

    # -- paged-KV bookkeeping (worker thread only) -----------------------
    def _alloc_block(self) -> Optional[int]:
        """Pool alloc with prefix-cache pressure relief: when the free
        list is empty, evict cold cached prefixes (LRU, only blocks no
        live slot references) until one frees."""
        bid = self._pool.alloc()
        while bid is None:
            if self._prefix.evict_lru() is None:
                return None
            bid = self._pool.alloc()
        return bid

    def _set_block_gauges(self):
        STAT_SET("serving.gen_kv_blocks_free", self._pool.free_count())

    def _adapt_spec_k(self, st: _SlotState, rate: float):
        """Fold one measured acceptance ratio into the slot's draft
        budget (spec_decode.update_spec_k). Gauge reflects the most
        recently adapted slot's budget."""
        from .spec_decode import update_spec_k
        from ..core.flags import FLAGS
        st.spec_k_cur, st.spec_acc_ewma, moved = update_spec_k(
            st.spec_k_cur, st.spec_acc_ewma, rate,
            k_max=self.spec_k, low=float(FLAGS.spec_adapt_low),
            high=float(FLAGS.spec_adapt_high))
        if moved < 0:
            STAT_ADD("serving.gen_spec_k_shrinks")
        elif moved > 0:
            STAT_ADD("serving.gen_spec_k_grows")
        STAT_SET("serving.gen_spec_k_effective", st.spec_k_cur)

    def _admit_trace(self, st: _SlotState, q: "_Queued"):
        """Queue -> prefill phase transition on the request's span tree
        (admission happens on the worker thread — the span rode the
        _Queued object across)."""
        st.span = q.span
        trace.end_span(q.qspan)
        st.phase_span = trace.start_span("prefill", parent=st.span)

    def _admit_locked(self) -> bool:
        """Move the queue head into a free slot. Paged mode additionally
        gates on block availability: shared prefix blocks come from the
        PrefixCache (refcounted, zero prefill cost), the rest are
        allocated upfront for the request's worst case — so a decode
        can never die mid-flight from pool exhaustion. Returns False
        (leaving the queue untouched) when the head cannot be placed
        yet."""
        q = self._queue[0]
        slot = self._slots.acquire()
        if slot is None:
            return False
        st = _SlotState(q.req, q.response, q.deadline, q.t_submit)
        if self.paged:
            prompt = q.req.prompt
            need = len(prompt) + q.req.max_new_tokens - 1
            # the last prompt position must stay writable (its KV is
            # written by this slot's first decode step), so the prefix
            # match is capped one token short of the prompt
            n_cached, shared = self._prefix.lookup(
                prompt, max_tokens=len(prompt) - 1)
            owned: List[int] = []
            missing = blocks_for_tokens(need, self.block_size) - \
                len(shared)
            while len(owned) < missing:
                bid = self._alloc_block()
                if bid is None:
                    break
                owned.append(bid)
            else:
                st.blocks = shared + owned
                st.n_cached = n_cached
                st.fed = n_cached
                st.cur = prompt[n_cached]
                STAT_ADD("serving.gen_prefix_hits" if n_cached
                         else "serving.gen_prefix_misses")
                self._set_block_gauges()
                self._admit_trace(st, q)
                if st.phase_span is not None and n_cached:
                    st.phase_span.set_attr("cached_tokens", n_cached)
                self._state[slot] = st
                self._queue.pop(0)
                return True
            # not enough blocks: roll back and wait for releases
            for bid in owned + shared:
                self._pool.decref(bid)
            self._slots.release(slot)
            self._set_block_gauges()
            return False
        self._admit_trace(st, q)
        self._state[slot] = st
        self._queue.pop(0)
        return True

    def _release_slot(self, i: int):
        """Retire slot i: in paged mode 'reset' IS this — the blocks go
        back to the pool (or stay resident for the prefix cache /
        other slots holding refs); the graph never wipes anything."""
        st = self._state[i]
        if st is not None and self.paged:
            for bid in st.blocks:
                self._pool.decref(bid)
            st.blocks = []
            self._set_block_gauges()
        self._state[i] = None
        self._slots.release(i)

    def _register_prefix(self, st: _SlotState):
        """After the first decode step, every full prompt block is
        immutable (all later writes land at positions past the prompt)
        — publish them to the prefix cache so the NEXT identical
        prefix skips its prefill."""
        bs = self.block_size
        n_full = len(st.req.prompt) // bs
        if n_full == 0:
            return
        hashes = self._prefix.chunk_hashes(st.req.prompt[:n_full * bs],
                                           bs)
        for j, h in enumerate(hashes):
            self._prefix.insert(h, st.blocks[j])
        self._set_block_gauges()

    # -- worker ----------------------------------------------------------
    def _expire_queued_locked(self, now) -> List[_Queued]:
        dead = [q for q in self._queue
                if q.deadline is not None and now >= q.deadline]
        if dead:
            self._queue = [q for q in self._queue if q not in dead]
        return dead

    def _finish(self, st: _SlotState, reason: str):
        now = time.perf_counter()
        e2e_ms = (now - st.t_submit) * 1e3
        if st.span is not None:
            # Aggregated device-sync attribution: one synthetic "fetch"
            # child of the decode phase carrying the summed fetch-block
            # time of every step this slot rode (NESTED, so the
            # queue+prefill+decode critical path doesn't double-count).
            if st.phase_span is not None and st.fetch_s > 0:
                trace.record_span(
                    "fetch", st.phase_span.t_start,
                    st.phase_span.t_start + st.fetch_s, st.phase_span,
                    attrs={"aggregated": True,
                           "fetch_ms": round(st.fetch_s * 1e3, 3)})
            trace.end_span(st.phase_span)
            st.span.attrs.update({
                "e2e_ms": round(e2e_ms, 3),
                "ttft_ms": None if st.ttft_ms is None
                else round(st.ttft_ms, 3),
                "tokens": len(st.generated),
                "finish_reason": reason,
                "cached_tokens": st.n_cached})
        st.response._complete({
            "tokens": list(st.generated),
            "finish_reason": reason,
            "ttft_ms": st.ttft_ms,
            "e2e_ms": e2e_ms,
            "cached_tokens": st.n_cached,
        })
        if _monitor_on():
            STAT_OBSERVE("serving.gen_e2e_ms", e2e_ms,
                         buckets=MS_BUCKETS,
                         exemplar=st.span.trace_id if st.span else None)

    def _worker_loop(self):
        # deferred: paddle_tpu/__init__ imports serving before the
        # models package exists, so this cannot be a module-level import
        from ..models import sampling
        B = self.max_slots
        while True:
            expired: List[_Queued] = []
            failed: List[_Queued] = []
            exit_loop = False
            with self._cond:
                now = time.perf_counter()
                expired = self._expire_queued_locked(now)
                if self._closed and not self._draining:
                    failed = self._queue
                    self._queue = []
                # admit queued requests into free slots (iteration-level
                # scheduling: this runs BETWEEN decode steps, so a slot
                # — and in paged mode its KV blocks — freed by the
                # previous step is reusable right now)
                while self._queue and self._slots.free_count() \
                        and self._admit_locked():
                    pass
                active_idx = [i for i in range(B)
                              if self._state[i] is not None]
                STAT_SET("serving.gen_queue_depth", len(self._queue))
                STAT_SET("serving.gen_active_slots", len(active_idx))
                if not active_idx:
                    if self._closed and not self._queue:
                        exit_loop = True
                    elif not (self._closed and not self._draining):
                        # generation goodput: no active slot = idle wait
                        t_idle0 = time.perf_counter()
                        self._cond.wait(0.05)
                        _goodput.gen_idle(time.perf_counter() - t_idle0)
            for q in expired:
                STAT_ADD("serving.gen_timeouts")
                trace.end_span(q.qspan, error="DeadlineExceededError")
                q.response._complete(error=DeadlineExceededError(
                    "generation request waited past its deadline"))
            for q in failed:
                trace.end_span(q.qspan, error="EngineClosedError")
                q.response._complete(error=EngineClosedError(
                    "generation engine shut down before the request "
                    "ran"))
            if self._closed and not self._draining:
                # fail whatever is mid-decode and exit
                for i in range(B):
                    st = self._state[i]
                    if st is not None:
                        st.response._complete(error=EngineClosedError(
                            "generation engine shut down mid-decode"))
                        self._release_slot(i)
                break
            if exit_loop:
                break
            if not active_idx:
                continue
            if self.paged:
                t_busy0 = time.perf_counter()
                # _kv_mutex: disagg export/adopt (serving/disagg.py)
                # mutates the same pools/PrefixCache between iterations
                with self._kv_mutex:
                    self._paged_iteration()
                _goodput.gen_busy(time.perf_counter() - t_busy0)
                continue

            # ---- one decode step over the full fixed-shape batch ----
            now = time.perf_counter()
            t_busy0 = now
            tokens = np.zeros((B, 1), np.int64)
            reset = np.zeros(B, np.float32)
            active = np.zeros(B, np.float32)
            stepped: List[int] = []
            for i in active_idx:
                st = self._state[i]
                if st.deadline is not None and now >= st.deadline:
                    STAT_ADD("serving.gen_timeouts")
                    st.response._complete(
                        error=DeadlineExceededError(
                            "generation deadline passed mid-decode"))
                    self._state[i] = None
                    self._slots.release(i)
                    continue
                tokens[i, 0] = st.cur
                reset[i] = 1.0 if st.needs_reset else 0.0
                active[i] = 1.0
                stepped.append(i)
            if not stepped:
                continue

            def _attempt():
                inj = _fault_injector()
                if inj is not None:
                    inj.pre_step("generation")
                return self._run_step(tokens, reset, active)

            try:
                # only the injector's pre-dispatch TransientFault is
                # retryable: once the real step ran, the KV cache
                # advanced and a replay would double-step the slots
                logits = self._step_retry.call(_attempt)
            except Exception as e:  # noqa: BLE001 — worker must survive
                if is_transient(e):
                    self._breaker.record_failure()
                STAT_ADD("resilience.gen_step_failures")
                for i in stepped:
                    st = self._state[i]
                    st.response._complete(error=RuntimeError(
                        f"decode step failed: {e!r}"))
                    self._state[i] = None
                    self._slots.release(i)
                continue
            self._breaker.record_success()
            if trace.enabled():
                lt = self.exe.last_step_timings
                if lt is not None:
                    for i in stepped:
                        self._state[i].fetch_s += lt["fetch_s"]
            inj = _fault_injector()
            if inj is not None:
                # step_nan at site=generation corrupts only the host
                # logits copy; the device KV state is untouched
                arrs = [logits]
                if inj.corrupt_fetches("generation", arrs):
                    logits = arrs[0]
            from ..core.flags import FLAGS
            if FLAGS.serving_nan_guard:
                bad = [i for i in stepped
                       if not np.all(np.isfinite(logits[i, 0]))]
                if bad:
                    self._breaker.record_failure()
                    STAT_ADD("resilience.gen_step_failures")
                    for i in bad:
                        st = self._state[i]
                        st.response._complete(error=RuntimeError(
                            "non-finite logits (cannot replay a "
                            "stateful decode step)"))
                        self._state[i] = None
                        self._slots.release(i)
                    stepped = [i for i in stepped if i not in bad]
                    if not stepped:
                        continue
            STAT_ADD("serving.gen_steps")
            if _monitor_on():
                STAT_OBSERVE("serving.gen_slot_occupancy",
                             len(stepped) / float(B),
                             buckets=FRACTION_BUCKETS)

            # ---- per-slot bookkeeping (sampling, streaming, finish) --
            t_step = time.perf_counter()
            for i in stepped:
                st = self._state[i]
                st.needs_reset = False
                st.fed += 1
                prompt = st.req.prompt
                if st.fed < len(prompt):
                    st.cur = prompt[st.fed]     # still prefilling
                    continue
                tok = sampling.sample_token(
                    logits[i, 0], temperature=st.req.temperature,
                    top_k=st.req.top_k, rng=st.rng)
                st.generated.append(tok)
                STAT_ADD("serving.gen_tokens")
                if len(st.generated) == 1:
                    st.ttft_ms = (t_step - st.t_submit) * 1e3
                    if _monitor_on():
                        STAT_OBSERVE("serving.gen_ttft_ms", st.ttft_ms,
                                     buckets=MS_BUCKETS)
                    if st.span is not None:
                        # prefill -> decode phase flip at first token
                        trace.end_span(st.phase_span)
                        st.phase_span = trace.start_span(
                            "decode", parent=st.span)
                elif _monitor_on() and st.t_prev_token is not None:
                    STAT_OBSERVE("serving.gen_inter_token_ms",
                                 (t_step - st.t_prev_token) * 1e3,
                                 buckets=MS_BUCKETS)
                st.t_prev_token = t_step
                if st.req.stream_cb is not None:
                    st.req.stream_cb(tok)
                    if st.phase_span is not None:
                        st.phase_span.add_event(
                            "stream_flush", token_index=len(st.generated))
                done_eos = (st.req.eos_id is not None
                            and tok == st.req.eos_id)
                if done_eos or len(st.generated) >= \
                        st.req.max_new_tokens:
                    self._finish(st, "eos" if done_eos else "length")
                    self._state[i] = None
                    self._slots.release(i)
                else:
                    st.cur = tok
            _goodput.gen_busy(time.perf_counter() - t_busy0)

    # -- paged iteration -------------------------------------------------
    def _paged_iteration(self):
        """One scheduler iteration of the paged engine: (1) chunked
        prefill — every slot still consuming its prompt retires up to
        one BLOCK of tokens through the prefill executable; (2) one
        decode step for every slot past its prompt. Both run the same
        two warmed executables every time (fixed shapes; muted rows
        write to the scratch block), so admission, chunk scheduling,
        release and prefix reuse never cost a compile. Long prompts
        therefore interleave with decode at block granularity instead
        of stalling the batch for O(prompt) steps."""
        from ..core.flags import FLAGS
        from ..models import sampling
        B = self.max_slots
        bs = self.block_size
        mb = self.step.max_blocks_per_slot
        now = time.perf_counter()
        for i in range(B):
            st = self._state[i]
            if st is not None and st.deadline is not None \
                    and now >= st.deadline:
                STAT_ADD("serving.gen_timeouts")
                st.response._complete(error=DeadlineExceededError(
                    "generation deadline passed mid-decode"))
                self._release_slot(i)

        def fill_row(arr_table, arr_start, i, st):
            arr_table[i, :len(st.blocks)] = st.blocks
            arr_start[i] = st.fed

        def run_guarded(prog, step, tokens, table, start, nvalid,
                        idx, what, site="generation"):
            """Shared failure envelope: injector pre-step faults retry
            (RetryPolicy), anything after the real dispatch fails the
            involved slots — KV already advanced, a replay would
            double-write. Returns the fetch or None. `site` names the
            fault-injection hook (prefill chunks get their own,
            "gen_prefill", so drills can slow prefill without touching
            decode — the disagg loadgen's machine-independent
            service-time knob)."""
            def _attempt():
                inj = _fault_injector()
                if inj is not None:
                    inj.pre_step(site)
                return self._run_paged(prog, step, tokens, table,
                                       start, nvalid)
            try:
                out = self._step_retry.call(_attempt)
            except Exception as e:  # noqa: BLE001 — worker must survive
                if is_transient(e):
                    self._breaker.record_failure()
                STAT_ADD("resilience.gen_step_failures")
                for i in idx:
                    st = self._state[i]
                    st.response._complete(error=RuntimeError(
                        f"{what} step failed: {e!r}"))
                    self._release_slot(i)
                return None
            self._breaker.record_success()
            if trace.enabled():
                lt = self.exe.last_step_timings
                if lt is not None:
                    for i in idx:
                        st = self._state[i]
                        if st is not None:
                            st.fetch_s += lt["fetch_s"]
            return out

        # ---- phase 1: chunked prefill ---------------------------------
        prefill_idx = [
            i for i in range(B) if self._state[i] is not None
            and self._state[i].fed < len(self._state[i].req.prompt) - 1]
        if prefill_idx:
            tokens = np.zeros((B, bs), np.int64)
            table = np.zeros((B, mb), np.int64)
            start = np.zeros(B, np.int64)
            nvalid = np.zeros(B, np.int64)
            chunk_n = {}
            for i in prefill_idx:
                st = self._state[i]
                prompt = st.req.prompt
                n = min(bs, len(prompt) - 1 - st.fed)
                tokens[i, :n] = prompt[st.fed:st.fed + n]
                fill_row(table, start, i, st)
                nvalid[i] = n
                chunk_n[i] = n
            probe = run_guarded(self._prefill_prog, self.prefill_step,
                                tokens, table, start, nvalid,
                                prefill_idx, "prefill",
                                site="gen_prefill")
            if probe is None:
                return
            if FLAGS.serving_nan_guard:
                bad = [i for i in prefill_idx
                       if not np.isfinite(probe[i])]
                if bad:
                    self._breaker.record_failure()
                    STAT_ADD("resilience.gen_step_failures")
                    for i in bad:
                        st = self._state[i]
                        st.response._complete(error=RuntimeError(
                            "non-finite activations in chunked prefill "
                            "(cannot replay a stateful step)"))
                        self._release_slot(i)
                    prefill_idx = [i for i in prefill_idx
                                   if i not in bad]
            for i in prefill_idx:
                st = self._state[i]
                st.fed += chunk_n[i]
                st.cur = st.req.prompt[st.fed]
                STAT_ADD("serving.gen_chunked_prefills")
                if st.phase_span is not None:
                    st.phase_span.add_event("prefill_chunk",
                                            tokens=chunk_n[i])

        # ---- phase 2: one decode (or spec verify) step ----------------
        decode_idx = [
            i for i in range(B) if self._state[i] is not None
            and self._state[i].fed >=
            len(self._state[i].req.prompt) - 1]
        if not decode_idx:
            return
        # speculative drafts (serving/spec_decode.py): host-side n-gram
        # lookup over each opted-in slot's prompt + generated tokens.
        # Any non-empty draft routes the WHOLE batch through the verify
        # executable — a draft-less row rides with n_valid=1, which is
        # semantically the decode step — while an all-empty round takes
        # the cheaper 1-token decode executable. Both were compiled in
        # start(), so the per-iteration choice never costs a compile.
        drafts = {}
        if self._drafter is not None:
            for i in decode_idx:
                st = self._state[i]
                if st.req.spec_decode is False:
                    continue
                # cap drafts to the blocks admission reserved (need-1
                # is the slot's last writable position) and to the
                # request's remaining token budget (the verify row
                # already emits one token beyond the accepted drafts)
                need = len(st.req.prompt) + st.req.max_new_tokens - 1
                if st.spec_k_cur is None:
                    st.spec_k_cur = self.spec_k
                k_slot = st.spec_k_cur if self.spec_adaptive \
                    else self.spec_k
                cap = min(k_slot, need - 1 - st.fed,
                          st.req.max_new_tokens - len(st.generated) - 1)
                if cap < 1:
                    continue
                d = self._drafter.draft(st.req.prompt + st.generated,
                                        cap)
                if d:
                    drafts[i] = d
        use_spec = bool(drafts)
        prog = self._spec_prog if use_spec else self._prog
        step = self.spec_step if use_spec else self.step
        T = self.spec_k + 1 if use_spec else 1
        tokens = np.zeros((B, T), np.int64)
        table = np.zeros((B, mb), np.int64)
        start = np.zeros(B, np.int64)
        nvalid = np.zeros(B, np.int64)
        n_draft = {}
        for i in decode_idx:
            st = self._state[i]
            d = drafts.get(i, ())
            n_draft[i] = len(d)
            tokens[i, 0] = st.cur
            if d:
                tokens[i, 1:1 + len(d)] = d
            fill_row(table, start, i, st)
            nvalid[i] = 1 + len(d)
        logits = run_guarded(prog, step, tokens, table, start, nvalid,
                             decode_idx,
                             "spec verify" if use_spec else "decode")
        if logits is None:
            return
        inj = _fault_injector()
        if inj is not None:
            arrs = [logits]
            if inj.corrupt_fetches("generation", arrs):
                logits = arrs[0]
        if FLAGS.serving_nan_guard:
            bad = [i for i in decode_idx
                   if not np.all(np.isfinite(
                       logits[i, :1 + n_draft[i]]))]
            if bad:
                self._breaker.record_failure()
                STAT_ADD("resilience.gen_step_failures")
                for i in bad:
                    st = self._state[i]
                    st.response._complete(error=RuntimeError(
                        "non-finite logits (cannot replay a stateful "
                        "decode step)"))
                    self._release_slot(i)
                decode_idx = [i for i in decode_idx if i not in bad]
                if not decode_idx:
                    return
        STAT_ADD("serving.gen_steps")
        if use_spec:
            STAT_ADD("serving.gen_spec_steps")
        if _monitor_on():
            STAT_OBSERVE("serving.gen_slot_occupancy",
                         len(decode_idx) / float(B),
                         buckets=FRACTION_BUCKETS)

        t_step = time.perf_counter()
        for i in decode_idx:
            st = self._state[i]
            nd = n_draft[i]
            if nd:
                STAT_ADD("serving.gen_spec_draft_proposed", nd)
                # verify row j's logits condition on exactly the tokens
                # a serial decode would have fed; accept_draft draws
                # through the same sample_token path with the slot's
                # rng, so emitted tokens are bit-identical to serial
                # decode at any temperature (models/sampling.py)
                emitted, n_acc = sampling.accept_draft(
                    logits[i, :nd + 1], tokens[i, 1:1 + nd],
                    temperature=st.req.temperature,
                    top_k=st.req.top_k, rng=st.rng)
                STAT_ADD("serving.gen_spec_draft_accepted", n_acc)
                if _monitor_on():
                    STAT_OBSERVE("serving.gen_spec_acceptance_rate",
                                 n_acc / nd, buckets=FRACTION_BUCKETS)
                    STAT_OBSERVE("serving.gen_spec_tokens_per_step",
                                 len(emitted),
                                 buckets=SPEC_TOKEN_BUCKETS)
                # the committed token + accepted drafts are now valid
                # KV; writes past fed (rejected tail) sit beyond the
                # cursor and are rewritten before any mask reads them
                st.fed += 1 + n_acc
                if self.spec_adaptive:
                    self._adapt_spec_k(st, n_acc / nd)
            else:
                emitted = [sampling.sample_token(
                    logits[i, 0], temperature=st.req.temperature,
                    top_k=st.req.top_k, rng=st.rng)]
                st.fed += 1
            finished = False
            for tok in emitted:
                st.generated.append(tok)
                STAT_ADD("serving.gen_tokens")
                if len(st.generated) == 1:
                    st.ttft_ms = (t_step - st.t_submit) * 1e3
                    if _monitor_on():
                        STAT_OBSERVE("serving.gen_ttft_ms", st.ttft_ms,
                                     buckets=MS_BUCKETS)
                    if st.span is not None:
                        # prefill -> decode phase flip at first token
                        trace.end_span(st.phase_span)
                        st.phase_span = trace.start_span(
                            "decode", parent=st.span)
                    if not st.registered:
                        # the whole prompt (every full block of it) is
                        # now resident and immutable — shareable from
                        # here on
                        self._register_prefix(st)
                        st.registered = True
                elif _monitor_on() and st.t_prev_token is not None:
                    STAT_OBSERVE("serving.gen_inter_token_ms",
                                 (t_step - st.t_prev_token) * 1e3,
                                 buckets=MS_BUCKETS)
                st.t_prev_token = t_step
                if st.req.stream_cb is not None:
                    st.req.stream_cb(tok)
                    if st.phase_span is not None:
                        st.phase_span.add_event(
                            "stream_flush",
                            token_index=len(st.generated))
                done_eos = (st.req.eos_id is not None
                            and tok == st.req.eos_id)
                if done_eos or len(st.generated) >= \
                        st.req.max_new_tokens:
                    self._finish(st, "eos" if done_eos else "length")
                    self._release_slot(i)
                    finished = True
                    break
            if not finished:
                st.cur = emitted[-1]

"""Dynamic request batching for the serving engine.

Reference: the reference framework's inference layer couples a predictor
pool to a request queue so concurrent clients share compiled engines; on
TPU the coupling is tighter — XLA executables are shape-specialized, so
an unconstrained batcher would compile once per novel (batch, seq) pair.
`BucketLadder` therefore quantizes every request onto a fixed grid of
batch and sequence buckets (the same shapes `ServingEngine.warmup`
precompiles), and `DynamicBatcher` coalesces compatible requests into one
padded batch, flushing on max-batch-size or max-wait-micros, with
per-request deadlines, bounded-queue backpressure, and graceful drain.

Threading model: any number of producer threads call `submit`; one (or a
few) consumer threads call `next_batch`. One lock + condition guards the
pending map; request completion happens outside the lock via per-request
events, so a slow client can never stall the dispatch path.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import trace
from ..monitor import STAT_ADD, STAT_OBSERVE, STAT_SET
from ..monitor import enabled as _monitor_on

__all__ = ["ServingError", "QueueFullError", "DeadlineExceededError",
           "EngineClosedError", "OverloadedError", "BucketLadder",
           "DynamicBatcher", "MS_BUCKETS", "FRACTION_BUCKETS",
           "BATCH_BUCKETS_HIST"]

# Histogram bucket sets for the serving.* stats (milliseconds and
# fractions — the monitor default is seconds-oriented).
MS_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
              250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)
FRACTION_BUCKETS = (0.0, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8,
                    0.9, 0.95)
BATCH_BUCKETS_HIST = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class ServingError(RuntimeError):
    """Base of every serving-engine request failure."""


class QueueFullError(ServingError):
    """Backpressure: the bounded request queue is at capacity."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before a worker completed it."""


class EngineClosedError(ServingError):
    """Submitted to (or pending in) a batcher that has shut down."""


class OverloadedError(ServingError):
    """Shed by an OPEN circuit breaker (paddle_tpu/resilience/
    breaker.py): the backend is failing, retry after `retry_after_s`.
    HTTP maps this to 503 with a Retry-After header."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class BucketLadder:
    """Fixed (batch, seq) shape grid.

    `batch_buckets` are the allowed padded batch sizes (ascending);
    `seq_buckets`, when set, are the allowed padded lengths of
    `seq_axis` (counted on the full array, batch dim included) for every
    feed whose runtime length varies. Every request is padded UP to the
    smallest bucket that fits, so the set of shapes that can reach the
    executor is finite — exactly the set `ServingEngine.warmup`
    precompiles.
    """

    def __init__(self, batch_buckets: Sequence[int],
                 seq_buckets: Optional[Sequence[int]] = None,
                 seq_axis: int = 1, pad_value: float = 0.0):
        if not batch_buckets:
            raise ValueError("batch_buckets must be non-empty")
        self.batch_buckets = tuple(sorted(int(b) for b in batch_buckets))
        if any(b <= 0 for b in self.batch_buckets):
            raise ValueError(f"batch buckets must be positive: "
                             f"{self.batch_buckets}")
        self.seq_buckets = tuple(sorted(int(s) for s in seq_buckets)) \
            if seq_buckets else None
        self.seq_axis = int(seq_axis)
        self.pad_value = pad_value

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    @staticmethod
    def _ceil(buckets: Tuple[int, ...], n: int, what: str) -> int:
        for b in buckets:
            if n <= b:
                return b
        raise ValueError(
            f"{what} {n} exceeds the largest bucket {buckets[-1]}")

    def bucket_batch(self, n: int) -> int:
        return self._ceil(self.batch_buckets, n, "batch size")

    def bucket_seq(self, t: int) -> int:
        if self.seq_buckets is None:
            return t
        return self._ceil(self.seq_buckets, t, "sequence length")

    def pad_seq(self, arr: np.ndarray) -> np.ndarray:
        """Pad `seq_axis` up to its bucket (no-op without seq buckets or
        for arrays too low-rank to have the axis)."""
        if self.seq_buckets is None or arr.ndim <= self.seq_axis:
            return arr
        t = arr.shape[self.seq_axis]
        bucket = self.bucket_seq(t)
        if bucket == t:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[self.seq_axis] = (0, bucket - t)
        return np.pad(arr, widths, constant_values=self.pad_value)

    def pad_batch(self, arr: np.ndarray, bucket: int) -> np.ndarray:
        """Pad axis 0 with zero rows up to the batch bucket."""
        n = arr.shape[0]
        if bucket == n:
            return arr
        widths = [(0, 0)] * arr.ndim
        widths[0] = (0, bucket - n)
        return np.pad(arr, widths, constant_values=self.pad_value)


class _Response:
    """Future-ish handle returned by DynamicBatcher.submit."""

    __slots__ = ("_event", "_value", "_error", "span")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None
        # Request span, completed in _complete — the one funnel every
        # success and failure path flows through, so the trace is
        # finished exactly once no matter which path filled us in.
        self.span = None

    def done(self) -> bool:
        return self._event.is_set()

    def _complete(self, value=None, error=None):
        self._value, self._error = value, error
        if self.span is not None:
            err = None if error is None else \
                f"{type(error).__name__}: {error}"
            trace.complete_request(self.span, error=err)
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        """Block for the outputs (a list of per-fetch ndarrays sliced to
        this request's rows). Raises the request's failure."""
        if not self._event.wait(timeout):
            raise DeadlineExceededError("result() wait timed out")
        if self._error is not None:
            raise self._error
        return self._value


class _Request:
    __slots__ = ("feed", "rows", "response", "t_enqueue", "deadline",
                 "span", "qspan")

    def __init__(self, feed, rows, deadline):
        self.feed = feed          # {name: seq-padded ndarray}
        self.rows = rows          # size of the request's batch dim
        self.response = _Response()
        self.t_enqueue = time.perf_counter()
        self.deadline = deadline  # perf_counter deadline or None
        # Request span + its queue-wait child. Spans cross the
        # submit -> worker thread hand-off ON this object (contextvars
        # do not follow requests across threads).
        self.span = None
        self.qspan = None


class _Batch:
    """One dispatchable group of shape-compatible requests."""

    __slots__ = ("requests", "signature", "t_dispatch")

    def __init__(self, requests, signature):
        self.requests = requests
        self.signature = signature
        self.t_dispatch = time.perf_counter()

    @property
    def rows(self) -> int:
        return sum(r.rows for r in self.requests)

    def build_feed(self, ladder: BucketLadder):
        """Concatenate the member requests along axis 0 and pad to the
        batch bucket. Returns (feed, batch_bucket, pad_waste_frac)."""
        bucket = ladder.bucket_batch(self.rows)
        feed: Dict[str, np.ndarray] = {}
        real = padded = 0
        for name in self.requests[0].feed:
            arr = np.concatenate([r.feed[name] for r in self.requests],
                                 axis=0) if len(self.requests) > 1 \
                else self.requests[0].feed[name]
            arr = ladder.pad_batch(arr, bucket)
            real += sum(r.feed[name].size for r in self.requests)
            padded += arr.size
            feed[name] = arr
        waste = 1.0 - (real / padded) if padded else 0.0
        return feed, bucket, waste

    def scatter(self, outputs: List[np.ndarray]):
        """Split each padded-batch output along axis 0 back to the
        member requests (the padded tail rows are dropped) and complete
        their responses."""
        offset = 0
        now = time.perf_counter()
        t_end = time.time()
        # Wall-clock start of the execute interval (dispatch -> now),
        # recorded retroactively under each member request's span.
        t_exec0 = t_end - (now - self.t_dispatch)
        for r in self.requests:
            trace.record_span("execute", t_exec0, t_end, r.span,
                              attrs={"batch_rows": self.rows})
            r.response._complete(
                [np.asarray(o[offset:offset + r.rows]) for o in outputs])
            if _monitor_on():
                STAT_OBSERVE("serving.e2e_ms",
                             (now - r.t_enqueue) * 1e3, buckets=MS_BUCKETS,
                             exemplar=r.span.trace_id if r.span else None)
            offset += r.rows

    def fail(self, error: Exception):
        for r in self.requests:
            r.response._complete(error=error)


class DynamicBatcher:
    """Thread-safe coalescing request queue over a BucketLadder.

    Producers `submit` feeds; a worker loop calls `next_batch`, which
    blocks until some shape-group either reached `max_batch_size` or its
    oldest request has waited `max_wait_us`, then returns the group as a
    `_Batch`. Requests whose deadline lapses while queued are failed
    with DeadlineExceededError; submissions past `queue_capacity`
    pending rows are rejected immediately with QueueFullError.
    """

    def __init__(self, ladder: BucketLadder, max_batch_size: int,
                 max_wait_us: int, queue_capacity: int,
                 default_timeout_ms: Optional[float] = None):
        if max_batch_size > ladder.max_batch:
            raise ValueError(
                f"max_batch_size {max_batch_size} exceeds the largest "
                f"batch bucket {ladder.max_batch}")
        self.ladder = ladder
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = max_wait_us / 1e6
        self.queue_capacity = int(queue_capacity)
        self.default_timeout_ms = default_timeout_ms
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # signature -> FIFO of _Request; signature is the per-example
        # shape/dtype key after seq-bucketing (batch dim excluded)
        self._pending: Dict[tuple, List[_Request]] = {}
        self._rows = 0
        self._closed = False
        self._draining = False

    # -- producer side --------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None) -> _Response:
        """Enqueue one request. `feed` maps input name -> ndarray whose
        axis 0 is this request's batch of rows (all inputs must agree).
        Returns a response handle; `.result()` blocks for the outputs.
        """
        if not feed:
            raise ValueError("empty feed")
        arrays = {}
        rows = None
        for name, val in feed.items():
            arr = np.asarray(val)
            if arr.ndim == 0:
                raise ValueError(f"feed {name!r} must have a batch dim")
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                raise ValueError(
                    f"feed {name!r} batch dim {arr.shape[0]} != {rows}")
            arrays[name] = self.ladder.pad_seq(arr)
        if rows == 0:
            raise ValueError("feed has zero rows")
        if rows > self.max_batch_size:
            raise ValueError(
                f"request rows {rows} exceed max_batch_size "
                f"{self.max_batch_size}; split the request")
        sig = tuple(sorted((n, a.shape[1:], str(a.dtype))
                           for n, a in arrays.items()))
        timeout_ms = timeout_ms if timeout_ms is not None \
            else self.default_timeout_ms
        deadline = time.perf_counter() + timeout_ms / 1e3 \
            if timeout_ms else None
        req = _Request(arrays, rows, deadline)
        if trace.enabled():
            # Child of the caller's span (http.request) when one is
            # current, else a new root trace.
            req.span = trace.start_span("serving.request",
                                        attrs={"rows": rows})
            req.response.span = req.span
            req.qspan = trace.start_span("queue", parent=req.span)
        try:
            with self._cond:
                if self._closed:
                    raise EngineClosedError("batcher is shut down")
                if self._rows + rows > self.queue_capacity:
                    STAT_ADD("serving.rejected")
                    raise QueueFullError(
                        f"queue at capacity ({self._rows}/"
                        f"{self.queue_capacity} rows pending)")
                self._pending.setdefault(sig, []).append(req)
                self._rows += rows
                STAT_ADD("serving.requests")
                STAT_SET("serving.queue_depth", self._rows)
                self._cond.notify_all()
        except ServingError as e:
            # Rejected before it was visible to any worker: the raise IS
            # the completion, so finish the trace here (errored -> the
            # tail rules keep it).
            trace.end_span(req.qspan, error=type(e).__name__)
            trace.complete_request(req.span,
                                   error=f"{type(e).__name__}: {e}")
            raise
        return req.response

    # -- consumer side --------------------------------------------------
    def _expire_locked(self, now: float) -> List[_Request]:
        dead = []
        for sig in list(self._pending):
            reqs = self._pending[sig]
            alive = []
            for r in reqs:
                if r.deadline is not None and now >= r.deadline:
                    dead.append(r)
                    self._rows -= r.rows
                else:
                    alive.append(r)
            if len(alive) != len(reqs):
                if alive:
                    self._pending[sig] = alive
                else:
                    del self._pending[sig]
        return dead

    def _pick_locked(self, now: float, force: bool):
        """The flushable group, or (None, wait_s) with the time until
        the earliest group matures. force flushes any non-empty group
        (drain path)."""
        best_sig, best_age = None, -1.0
        wait = None
        for sig, reqs in self._pending.items():
            rows = sum(r.rows for r in reqs)
            age = now - reqs[0].t_enqueue
            if force or rows >= self.max_batch_size \
                    or age >= self.max_wait_s:
                if age > best_age:
                    best_sig, best_age = sig, age
            else:
                remaining = self.max_wait_s - age
                if r_dl := [r.deadline for r in reqs
                            if r.deadline is not None]:
                    remaining = min(remaining, max(min(r_dl) - now, 0.0))
                wait = remaining if wait is None else min(wait, remaining)
        if best_sig is None:
            return None, wait
        reqs = self._pending[best_sig]
        take, rows = [], 0
        while reqs and rows + reqs[0].rows <= self.max_batch_size:
            r = reqs.pop(0)
            take.append(r)
            rows += r.rows
        if not reqs:
            del self._pending[best_sig]
        self._rows -= rows
        return _Batch(take, best_sig), None

    def next_batch(self, timeout: Optional[float] = None):
        """Block until a batch is ready (or `timeout` elapses -> None;
        closed + empty -> None). Expired requests are failed inline."""
        deadline = time.perf_counter() + timeout \
            if timeout is not None else None
        expired: List[_Request] = []
        batch = None
        with self._cond:
            while True:
                now = time.perf_counter()
                expired.extend(self._expire_locked(now))
                batch, wait = self._pick_locked(
                    now, force=self._draining)
                if batch is not None or (self._closed
                                         and not self._pending):
                    break
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        break
                    wait = remaining if wait is None \
                        else min(wait, remaining)
                # no pending work and no timeout: sleep until notified
                self._cond.wait(wait)
            if batch is not None:
                STAT_SET("serving.queue_depth", self._rows)
        for r in expired:
            STAT_ADD("serving.timeouts")
            trace.end_span(r.qspan, error="DeadlineExceededError")
            r.response._complete(error=DeadlineExceededError(
                f"request waited past its "
                f"{'deadline' if r.deadline else 'timeout'}"))
        if batch is not None:
            for r in batch.requests:
                trace.end_span(r.qspan)
                if _monitor_on():
                    STAT_OBSERVE("serving.queue_wait_ms",
                                 (batch.t_dispatch - r.t_enqueue) * 1e3,
                                 buckets=MS_BUCKETS)
        return batch

    # -- lifecycle ------------------------------------------------------
    def pending_rows(self) -> int:
        with self._lock:
            return self._rows

    def close(self, drain: bool = True):
        """Stop accepting submissions. drain=True leaves queued requests
        for the worker to finish (and flushes immature groups at once);
        drain=False fails them with EngineClosedError."""
        failed: List[_Request] = []
        with self._cond:
            self._closed = True
            self._draining = drain
            if not drain:
                for reqs in self._pending.values():
                    failed.extend(reqs)
                self._pending.clear()
                self._rows = 0
            STAT_SET("serving.queue_depth", self._rows)
            self._cond.notify_all()
        for r in failed:
            r.response._complete(error=EngineClosedError(
                "batcher shut down before the request ran"))

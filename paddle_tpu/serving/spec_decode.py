"""Speculative decoding: host-side n-gram drafter for the paged engine.

Decode is one token per slot per step by construction — the fixed-shape
executable contract (docs/serving.md) forbids feeding a variable number
of tokens. Speculative decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding", arXiv 2211.17192) breaks the
one-token ceiling without breaking the contract: a cheap DRAFTER
proposes k candidate continuation tokens, one batched VERIFY step
scores all k+1 positions through the paged decode graph
(`models/gpt.py:build_spec_verify_step`, a `[max_slots, k+1]`
fixed-shape sibling of the decode step), and the host accepts the
longest prefix the target model agrees with
(`models/sampling.py:accept_draft`). Every accepted token costs zero
extra forward passes; a full rejection degenerates to exactly the
single-token step.

The drafter here is the prompt-lookup / n-gram variant (no second
model, no extra weights, nothing on the device): LLM serving traffic is
full of verbatim repetition — retrieved documents echoed into answers,
code identifiers, templated JSON — so the best guess for what follows
the current context suffix is *what followed it last time it appeared*.
`NgramDrafter.draft` suffix-matches the slot's prompt + generated
tokens against itself (longest n-gram first, most recent occurrence
wins) and proposes the up-to-k tokens that followed.

Drafting is pure host-side Python over the token lists the scheduler
already owns: no flags reach the graph, no shapes change, and a slot
with no match simply rides the verify step with `n_valid = 1`
(semantically identical to the plain decode step). Correctness is
sampling-path identity, not heuristics: verify logits at position j
condition on exactly the tokens a serial decode would have fed, and
`accept_draft` draws through the SAME `sample_token` path with the
slot's own rng, so outputs are token-for-token identical to the serial
reference at any temperature (tests/test_spec_decode.py).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

__all__ = ["NgramDrafter", "update_spec_k"]


def update_spec_k(cur: int, ewma: Optional[float], rate: float,
                  k_max: int, low: float = 0.3, high: float = 0.8,
                  alpha: float = 0.5) -> Tuple[int, float, int]:
    """Acceptance-aware draft-length controller (pure, per slot).

    Folds this iteration's measured acceptance `rate` (accepted /
    proposed, in [0, 1]) into an EWMA and moves the slot's draft budget
    one step: below `low` the budget shrinks (drafting is not paying
    for the verify premium), above `high` it grows back toward `k_max`.
    Returns `(new_k, new_ewma, moved)` with moved in {-1, 0, +1}.

    Only the number of PROPOSED tokens changes — verification and
    acceptance stay sampling-path identical, so adapting k can never
    change emitted tokens, only how much verify compute is wasted.
    """
    rate = min(1.0, max(0.0, float(rate)))
    ewma = rate if ewma is None else alpha * rate + (1 - alpha) * ewma
    moved = 0
    if ewma < low and cur > 1:
        cur -= 1
        moved = -1
    elif ewma > high and cur < k_max:
        cur += 1
        moved = 1
    return cur, ewma, moved


class NgramDrafter:
    """Prompt-lookup drafter: propose what followed this suffix before.

    `max_ngram` bounds the suffix length tried (longest first — a
    longer match is stronger evidence the continuation repeats);
    `k` caps the tokens proposed per call. Stateless and thread-free:
    the engine worker calls `draft` between decode steps with each
    slot's full known context.
    """

    def __init__(self, max_ngram: int = 3, k: int = 4):
        self.max_ngram = int(max_ngram)
        self.k = int(k)

    def draft(self, context: Sequence[int], k: int = 0) -> List[int]:
        """Up to min(k or self.k, ...) draft tokens continuing `context`.

        Tries suffix lengths n = max_ngram..1: find the MOST RECENT
        earlier occurrence of the length-n suffix inside `context`
        itself and return the tokens that followed it. Returns [] when
        nothing matches (unique suffix, context too short, k <= 0) —
        the caller then falls back to the plain decode step.
        """
        k = int(k) if k else self.k
        ctx = [int(t) for t in context]
        L = len(ctx)
        if k <= 0 or self.max_ngram <= 0 or L < 2:
            return []
        for n in range(min(self.max_ngram, L - 1), 0, -1):
            suffix = ctx[L - n:]
            # scan right-to-left so the most recent occurrence wins —
            # recent text is the best predictor of what repeats next
            for i in range(L - n - 1, -1, -1):
                if ctx[i:i + n] == suffix:
                    out = ctx[i + n:i + n + k]
                    if out:
                        return out
        return []

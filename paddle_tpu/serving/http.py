"""Stdlib HTTP front end for ServingEngine.

Endpoints (JSON over ThreadingHTTPServer — each client connection gets
its own handler thread, which blocks in `engine.predict` so the dynamic
batcher sees genuine concurrency):

- ``POST /v1/predict``  body ``{"inputs": {name: nested list},
  "timeout_ms": optional}`` -> ``{"outputs": {name: nested list},
  "shapes": {...}}``; 400 malformed, 503 queue-full/closed (the
  backpressure status clients should retry with backoff), 504 deadline.
- ``GET /healthz``      -> 200 ``{"status": "ok"}`` once the engine is
  warmed and ready, 503 before/after.
- ``GET /metrics``      -> the same Prometheus text the monitor's scrape
  endpoint serves (monitor.prometheus_text), so one port serves both
  traffic and observability.
"""
from __future__ import annotations

import json
import threading
from typing import Optional

import numpy as np

from ..monitor import STAT_ADD, prometheus_text
from .batcher import (DeadlineExceededError, EngineClosedError,
                      QueueFullError)
from .engine import ServingEngine

__all__ = ["ServingHTTPServer", "serve"]


class ServingHTTPServer:
    """Owns the listening socket + serve_forever thread. `port=0` binds
    an ephemeral port (read it back from `.port` — tests do)."""

    def __init__(self, engine: ServingEngine, port: int = 0,
                 host: str = "127.0.0.1"):
        import http.server

        eng = engine

        class _Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                STAT_ADD("serving.http_requests")
                if self.path.startswith("/healthz"):
                    if eng.ready:
                        self._reply(200, {"status": "ok"})
                    else:
                        self._reply(503, {"status": "not ready"})
                elif self.path.startswith("/metrics"):
                    body = prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                STAT_ADD("serving.http_requests")
                if not self.path.startswith("/v1/predict"):
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    inputs = req["inputs"]
                    if not isinstance(inputs, dict) or not inputs:
                        raise ValueError(
                            "'inputs' must be a non-empty object")
                    feed = {str(k): np.asarray(v)
                            for k, v in inputs.items()}
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                try:
                    outs = eng.predict(
                        feed, timeout_ms=req.get("timeout_ms"))
                except QueueFullError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True})
                    return
                except DeadlineExceededError as e:
                    self._reply(504, {"error": str(e)})
                    return
                except EngineClosedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": False})
                    return
                except ValueError as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                names = eng.output_names()
                self._reply(200, {
                    "outputs": {n: o.tolist()
                                for n, o in zip(names, outs)},
                    "shapes": {n: list(o.shape)
                               for n, o in zip(names, outs)},
                })

            def log_message(self, *args):
                pass  # request logging goes through the monitor, not
                # stderr

        self.engine = engine
        self._srv = http.server.ThreadingHTTPServer((host, port),
                                                    _Handler)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="ptn-serving-http",
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


def serve(engine: ServingEngine,
          port: Optional[int] = None) -> ServingHTTPServer:
    """Start the engine (if not already started) and expose it over
    HTTP. port=None reads EngineConfig.http_port (itself defaulted from
    FLAGS_serving_http_port; 0 binds an ephemeral port)."""
    engine.start()
    if port is None:
        port = engine.config.http_port
    return ServingHTTPServer(engine, port=port)

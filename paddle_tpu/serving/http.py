"""Stdlib HTTP front end for ServingEngine / GenerationEngine.

Endpoints (JSON over ThreadingHTTPServer — each client connection gets
its own handler thread, which blocks in `engine.predict` /
`gen_engine.generate` so the batching layers see genuine concurrency):

- ``POST /v1/predict``  body ``{"inputs": {name: nested list},
  "timeout_ms": optional}`` -> ``{"outputs": {name: nested list},
  "shapes": {...}}``; 400 malformed, 503 queue-full/closed (the
  backpressure status clients should retry with backoff), 504 deadline.
- ``POST /v1/generate`` body ``{"prompt": [token ids],
  "max_new_tokens": n, "temperature"/"top_k"/"eos_id"/"seed"/
  "timeout_ms": optional}`` -> ``{"tokens": [...], "finish_reason":
  "length"|"eos", "ttft_ms", "e2e_ms"}`` from the continuous-batching
  GenerationEngine; same 400/503/504 error mapping. 404 when the server
  was started without a generation engine.
- ``GET /healthz``      -> 200 ``{"status": "ok"}`` once every attached
  engine is warmed and ready, 503 before/after.
- ``GET /metrics``      -> the same Prometheus text the monitor's scrape
  endpoint serves (monitor.prometheus_text), so one port serves both
  traffic and observability.
"""
from __future__ import annotations

import json
import threading
from typing import Optional

import numpy as np

from ..monitor import STAT_ADD, prometheus_text
from .batcher import (DeadlineExceededError, EngineClosedError,
                      QueueFullError)
from .engine import ServingEngine

__all__ = ["ServingHTTPServer", "serve"]


class ServingHTTPServer:
    """Owns the listening socket + serve_forever thread. `port=0` binds
    an ephemeral port (read it back from `.port` — tests do).

    Attach a `ServingEngine` (/v1/predict), a `GenerationEngine`
    (/v1/generate), or both on one port; an absent engine's route
    answers 404."""

    def __init__(self, engine: Optional[ServingEngine] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 gen_engine=None):
        import http.server

        if engine is None and gen_engine is None:
            raise ValueError("ServingHTTPServer needs an engine and/or "
                             "a gen_engine")
        eng = engine
        gen = gen_engine

        class _Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                STAT_ADD("serving.http_requests")
                if self.path.startswith("/healthz"):
                    if all(e.ready for e in (eng, gen)
                           if e is not None):
                        self._reply(200, {"status": "ok"})
                    else:
                        self._reply(503, {"status": "not ready"})
                elif self.path.startswith("/metrics"):
                    body = prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                STAT_ADD("serving.http_requests")
                if self.path.startswith("/v1/generate"):
                    self._generate()
                    return
                if not self.path.startswith("/v1/predict") \
                        or eng is None:
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    inputs = req["inputs"]
                    if not isinstance(inputs, dict) or not inputs:
                        raise ValueError(
                            "'inputs' must be a non-empty object")
                    feed = {str(k): np.asarray(v)
                            for k, v in inputs.items()}
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                try:
                    outs = eng.predict(
                        feed, timeout_ms=req.get("timeout_ms"))
                except QueueFullError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True})
                    return
                except DeadlineExceededError as e:
                    self._reply(504, {"error": str(e)})
                    return
                except EngineClosedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": False})
                    return
                except ValueError as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                names = eng.output_names()
                self._reply(200, {
                    "outputs": {n: o.tolist()
                                for n, o in zip(names, outs)},
                    "shapes": {n: list(o.shape)
                               for n, o in zip(names, outs)},
                })

            def _generate(self):
                from .generation import GenerationRequest
                if gen is None:
                    self._reply(404, {"error": "no generation engine "
                                               "attached"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    greq = GenerationRequest(
                        prompt=req["prompt"],
                        max_new_tokens=req["max_new_tokens"],
                        temperature=req.get("temperature", 0.0),
                        top_k=req.get("top_k", 0),
                        eos_id=req.get("eos_id"),
                        timeout_ms=req.get("timeout_ms"),
                        seed=req.get("seed", 0))
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                try:
                    out = gen.submit(greq).result()
                except QueueFullError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True})
                    return
                except DeadlineExceededError as e:
                    self._reply(504, {"error": str(e)})
                    return
                except EngineClosedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": False})
                    return
                except ValueError as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                self._reply(200, out)

            def log_message(self, *args):
                pass  # request logging goes through the monitor, not
                # stderr

        self.engine = engine
        self._srv = http.server.ThreadingHTTPServer((host, port),
                                                    _Handler)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="ptn-serving-http",
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


def serve(engine: Optional[ServingEngine] = None,
          port: Optional[int] = None,
          gen_engine=None) -> ServingHTTPServer:
    """Start the engine(s) (if not already started) and expose them
    over HTTP. port=None reads EngineConfig.http_port when a
    ServingEngine is attached (itself defaulted from
    FLAGS_serving_http_port; 0 binds an ephemeral port)."""
    if engine is not None:
        engine.start()
    if gen_engine is not None:
        gen_engine.start()
    if port is None:
        port = engine.config.http_port if engine is not None else 0
    return ServingHTTPServer(engine, port=port, gen_engine=gen_engine)

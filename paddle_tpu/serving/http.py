"""Stdlib HTTP front end for ServingEngine / GenerationEngine.

Endpoints (JSON over ThreadingHTTPServer — each client connection gets
its own handler thread, which blocks in `engine.predict` /
`gen_engine.generate` so the batching layers see genuine concurrency):

- ``POST /v1/predict``  body ``{"inputs": {name: nested list},
  "timeout_ms": optional}`` -> ``{"outputs": {name: nested list},
  "shapes": {...}}``; 400 malformed, 503 queue-full/closed (the
  backpressure status clients should retry with backoff), 504 deadline.
- ``POST /v1/generate`` body ``{"prompt": [token ids],
  "max_new_tokens": n, "temperature"/"top_k"/"eos_id"/"seed"/
  "timeout_ms"/"spec_decode": optional}`` -> ``{"tokens": [...],
  "finish_reason": "length"|"eos", "ttft_ms", "e2e_ms"}`` from the
  continuous-batching
  GenerationEngine; same 400/503/504 error mapping. 404 when the server
  was started without a generation engine.
- ``POST /v1/kv/export`` body ``{"prompt": [token ids],
  "run_prefill": optional}`` -> a ``kv_wire`` shipment (the prompt's
  full-block KV prefix, prefilled locally if needed), and
  ``POST /v1/kv/adopt`` body = a shipment -> adoption summary; the
  disaggregated-fleet transfer hop (serving/disagg.py,
  docs/serving.md). 404 unless a *paged* generation engine is attached.
- ``GET /healthz``      -> aggregated engine health. 200 with
  ``{"state": "ok"|"degraded", ...}`` while every attached engine is
  ready (degraded = some circuit breaker is half-open and probing);
  503 with ``{"state": "warming"|"open"|"stopped", ...}`` otherwise —
  ``warming`` until warmup() completes, ``open`` (plus a
  ``Retry-After`` header) while a breaker is shedding load.
- ``GET /metrics``      -> the same Prometheus text the monitor's scrape
  endpoint serves (monitor.prometheus_text), so one port serves both
  traffic and observability — including ``ALERTS{...}`` series and
  ``alerts.*`` stats when the SLO engine is running.
- ``GET /alertz``       -> the alert engine's full rule/state dump
  (monitor_alerts.alertz_dict): every rule with its state
  (inactive/pending/firing), last value, windows, and the incident
  bundle path of the current firing. Always 200 — an alert never flips
  health; ``/healthz`` detail carries an ``alerts_firing`` count for
  operators instead.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Optional

import numpy as np

from .. import monitor_alerts, trace
from ..monitor import STAT_ADD, prometheus_text
from .batcher import (DeadlineExceededError, EngineClosedError,
                      OverloadedError, QueueFullError)
from .engine import ServingEngine

# severity order for aggregating per-engine health states into one
# /healthz verdict (worst wins); ok/degraded answer 200, the rest 503
_STATE_RANK = {"ready": 0, "degraded": 1, "warming": 2, "open": 3,
               "stopped": 4}


def _retry_after_hdr(e: OverloadedError):
    s = getattr(e, "retry_after_s", 0.0) or 0.0
    if s <= 0:
        return None
    return {"Retry-After": str(max(1, int(round(s))))}

__all__ = ["ServingHTTPServer", "serve"]


class ServingHTTPServer:
    """Owns the listening socket + serve_forever thread. `port=0` binds
    an ephemeral port (read it back from `.port` — tests do).

    Attach a `ServingEngine` (/v1/predict), a `GenerationEngine`
    (/v1/generate), or both on one port; an absent engine's route
    answers 404."""

    def __init__(self, engine: Optional[ServingEngine] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 gen_engine=None):
        import http.server

        if engine is None and gen_engine is None:
            raise ValueError("ServingHTTPServer needs an engine and/or "
                             "a gen_engine")
        eng = engine
        gen = gen_engine
        # In-flight POST accounting so close(drain=True) can wait for
        # work already inside an engine instead of resetting the
        # connection under it (replica restarts behind the router must
        # not surface as wrong answers).
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._draining = False
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # per-request trace state (each request is handled
            # start-to-finish on one connection thread)
            _span = None
            _last_code = None

            def _reply(self, code: int, payload: dict, headers=None):
                self._last_code = code
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if self._span is not None:
                    # Router-ready response identity: clients (and the
                    # future multi-replica router) correlate by request
                    # id; the traceparent echo lets a caller that did
                    # NOT send one adopt the trace this server opened.
                    self._span.set_attr("http.status", code)
                    self.send_header("X-Request-Id",
                                     self._span.trace_id)
                    self.send_header(
                        "traceparent",
                        trace.format_traceparent(self._span))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _healthz(self):
                worst = "ready"
                retry_after = 0.0
                detail = {}
                for name, e in (("predict", eng), ("generate", gen)):
                    if e is None:
                        continue
                    if hasattr(e, "health"):
                        h = e.health()
                    else:
                        h = {"state": "ready" if e.ready
                             else "warming"}
                    if hasattr(e, "post_warmup_compiles"):
                        h = dict(h)
                        h["post_warmup_compiles"] = \
                            e.post_warmup_compiles()
                    if hasattr(e, "kv_block_stats"):
                        h["kv"] = e.kv_block_stats()
                    detail[name] = h
                    if _STATE_RANK.get(h["state"], 4) > \
                            _STATE_RANK.get(worst, 4):
                        worst = h["state"]
                    retry_after = max(retry_after,
                                      h.get("retry_after_s") or 0.0)
                body = {"state": "ok" if worst == "ready" else worst,
                        "engines": detail,
                        # informational: firing alerts never change the
                        # health verdict (alerts page humans; healthz
                        # steers load balancers)
                        "alerts_firing": monitor_alerts.firing_count()}
                if worst in ("ready", "degraded"):
                    self._reply(200, body)
                else:
                    hdrs = None
                    if worst == "open" and retry_after > 0:
                        hdrs = {"Retry-After":
                                str(max(1, int(round(retry_after))))}
                    self._reply(503, body, headers=hdrs)

            def do_GET(self):
                STAT_ADD("serving.http_requests")
                if self.path.startswith("/healthz"):
                    self._healthz()
                elif self.path.startswith("/metrics"):
                    body = prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.startswith("/alertz"):
                    self._reply(200, monitor_alerts.alertz_dict())
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                STAT_ADD("serving.http_requests")
                with outer._inflight_cv:
                    if outer._draining:
                        draining = True
                    else:
                        draining = False
                        outer._inflight += 1
                if draining:
                    # Keep-alive connections outlive shutdown(); refuse
                    # new work with the retryable backpressure status
                    # and drop the connection so clients re-dial.
                    self._reply(503, {"error": "server is draining",
                                      "retryable": True})
                    self.close_connection = True
                    return
                try:
                    self._do_post()
                finally:
                    with outer._inflight_cv:
                        outer._inflight -= 1
                        if outer._inflight == 0:
                            outer._inflight_cv.notify_all()

            def _do_post(self):
                self._span = None
                self._last_code = None
                if trace.enabled():
                    # W3C trace-context ingress: continue the caller's
                    # trace when a valid traceparent arrived, else open
                    # a new root. The span is contextvar-current for
                    # the handler body, so the batcher/generation
                    # submit() spans parent under it.
                    remote = trace.parse_traceparent(
                        self.headers.get("traceparent"))
                    self._span = trace.start_span(
                        "http.request", remote=remote,
                        attrs={"method": "POST",
                               "path": self.path.split("?")[0]})
                try:
                    with trace.use_span(self._span):
                        self._route_post()
                except BaseException as e:
                    trace.finish_trace(
                        self._span, error=f"{type(e).__name__}: {e}")
                    self._span = None
                    raise
                else:
                    code = self._last_code
                    err = f"http {code}" \
                        if code is not None and code >= 400 else None
                    trace.finish_trace(self._span, error=err)
                    self._span = None

            def _route_post(self):
                if self.path.startswith("/v1/generate"):
                    self._generate()
                    return
                if self.path.startswith("/v1/kv/"):
                    self._kv()
                    return
                if not self.path.startswith("/v1/predict") \
                        or eng is None:
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    inputs = req["inputs"]
                    if not isinstance(inputs, dict) or not inputs:
                        raise ValueError(
                            "'inputs' must be a non-empty object")
                    feed = {str(k): np.asarray(v)
                            for k, v in inputs.items()}
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                try:
                    outs = eng.predict(
                        feed, timeout_ms=req.get("timeout_ms"))
                except OverloadedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True},
                                headers=_retry_after_hdr(e))
                    return
                except QueueFullError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True})
                    return
                except DeadlineExceededError as e:
                    self._reply(504, {"error": str(e)})
                    return
                except EngineClosedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": False})
                    return
                except ValueError as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                names = eng.output_names()
                self._reply(200, {
                    "outputs": {n: o.tolist()
                                for n, o in zip(names, outs)},
                    "shapes": {n: list(o.shape)
                               for n, o in zip(names, outs)},
                })

            def _generate(self):
                from .generation import GenerationRequest
                if gen is None:
                    self._reply(404, {"error": "no generation engine "
                                               "attached"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    greq = GenerationRequest(
                        prompt=req["prompt"],
                        max_new_tokens=req["max_new_tokens"],
                        temperature=req.get("temperature", 0.0),
                        top_k=req.get("top_k", 0),
                        eos_id=req.get("eos_id"),
                        timeout_ms=req.get("timeout_ms"),
                        seed=req.get("seed", 0),
                        spec_decode=req.get("spec_decode"))
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                try:
                    out = gen.submit(greq).result()
                except OverloadedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True},
                                headers=_retry_after_hdr(e))
                    return
                except QueueFullError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True})
                    return
                except DeadlineExceededError as e:
                    self._reply(504, {"error": str(e)})
                    return
                except EngineClosedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": False})
                    return
                except ValueError as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                self._reply(200, out)

            def _kv(self):
                """Disaggregated KV transfer (serving/disagg.py):
                /v1/kv/export packs a prompt's full-block prefix into a
                kv_wire shipment; /v1/kv/adopt unpacks one into the
                local pool. 404 unless a paged generation engine is
                attached."""
                from . import disagg
                if gen is None or not getattr(gen, "paged", False):
                    self._reply(404, {"error": "no paged generation "
                                               "engine attached"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                try:
                    if self.path.startswith("/v1/kv/export"):
                        out = disagg.export_prefix(
                            gen, req["prompt"],
                            run_prefill=bool(
                                req.get("run_prefill", True)))
                    elif self.path.startswith("/v1/kv/adopt"):
                        out = disagg.adopt_prefix(gen, req)
                    else:
                        self._reply(404, {"error":
                                          f"no route {self.path}"})
                        return
                except (KeyError, ValueError, TypeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                except OverloadedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True},
                                headers=_retry_after_hdr(e))
                    return
                except QueueFullError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True})
                    return
                except DeadlineExceededError as e:
                    self._reply(504, {"error": str(e)})
                    return
                except EngineClosedError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": False})
                    return
                self._reply(200, out)

            def log_message(self, *args):
                pass  # request logging goes through the monitor, not
                # stderr

        self.engine = engine
        # SLO alerting rides on the serving lifecycle: a front end with
        # FLAGS_alert_rules set gets the background evaluator for free
        # (no-op when no rules are configured).
        monitor_alerts.maybe_start()
        self._srv = http.server.ThreadingHTTPServer((host, port),
                                                    _Handler)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="ptn-serving-http",
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def inflight(self) -> int:
        with self._inflight_cv:
            return self._inflight

    def close(self, drain: bool = True, timeout: float = 10.0):
        """Stop accepting, optionally wait (bounded) for in-flight POSTs
        to finish, then release the socket. Requests arriving on live
        keep-alive connections after close() begins answer a retryable
        503 instead of a connection reset."""
        with self._inflight_cv:
            self._draining = True
        self._srv.shutdown()
        if drain:
            deadline = time.monotonic() + max(0.0, timeout)
            with self._inflight_cv:
                while self._inflight > 0:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._inflight_cv.wait(left)
        self._srv.server_close()

    # the router's replica lifecycle speaks stop(); same semantics
    stop = close


def serve(engine: Optional[ServingEngine] = None,
          port: Optional[int] = None,
          gen_engine=None,
          async_start: bool = False) -> ServingHTTPServer:
    """Start the engine(s) (if not already started) and expose them
    over HTTP. port=None reads EngineConfig.http_port when a
    ServingEngine is attached (itself defaulted from
    FLAGS_serving_http_port; 0 binds an ephemeral port).

    async_start=True binds the port first and runs the engine starts
    (warmup compiles) on a background thread, so /healthz answers 503
    ``{"state": "warming"}`` during warmup instead of the connection
    being refused — the readiness-probe contract load balancers
    expect."""
    def _start_engines():
        if engine is not None:
            engine.start()
        if gen_engine is not None:
            gen_engine.start()

    if port is None:
        port = engine.config.http_port if engine is not None else 0
    if async_start:
        srv = ServingHTTPServer(engine, port=port,
                                gen_engine=gen_engine)
        threading.Thread(target=_start_engines,
                         name="ptn-serving-warmup",
                         daemon=True).start()
        return srv
    _start_engines()
    return ServingHTTPServer(engine, port=port, gen_engine=gen_engine)

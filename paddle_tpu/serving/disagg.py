"""Disaggregated prefill/decode serving: cross-process KV transfer and
the fleet-level content-addressed prefix store.

Three pieces:

- `export_prefix(engine, prompt)`: on a *prefill* worker, make sure a
  prompt's full blocks are resident in the local PrefixCache (running
  chunked prefill through the engine's existing compiled executable if
  they are not), then pack those pool rows into a `kv_wire` shipment.
- `adopt_prefix(engine, payload)`: on a *decode* worker, unpack a
  shipment into freshly allocated BlockPool blocks and register them in
  the local PrefixCache under their chain hashes — the normal
  refcount/incref path, so eviction and sharing work exactly as for
  locally prefilled blocks, and the next `submit` of a matching prompt
  takes the ordinary prefix-hit fast path with zero extra compiles.
- `FleetPrefixStore`: the router-side registry mapping chain hashes to
  the replica names that hold them, so two-phase dispatch can skip the
  prefill hop entirely when the target decode worker already owns the
  prefix, or fetch it from whichever peer does.

Determinism: same weights + same tokens + same absolute positions +
same compiled graph on the same backend produce bit-identical KV, so a
decode worker continuing on adopted blocks emits exactly the tokens
the unified engine would.

Engine access is serialized against the engine's worker thread via
`engine._kv_mutex` (held by the worker around each paged iteration),
because BlockPool/PrefixCache are not thread-safe on their own.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence, Set

import numpy as np

from ..core.flags import FLAGS
from ..monitor import STAT_ADD
from . import kv_wire
from .kv_blocks import PrefixCache


def _require_paged(engine):
    if not getattr(engine, "paged", False):
        raise ValueError(
            "disaggregated KV transfer needs a paged engine "
            "(FLAGS_gen_paged_kv / paged=True)")


def _full_hashes(engine, prompt: Sequence[int]) -> List[str]:
    n_full = len(prompt) // engine.block_size
    return PrefixCache.chunk_hashes(
        list(prompt)[:n_full * engine.block_size], engine.block_size)


def _resident_depth(engine, prompt: Sequence[int]) -> int:
    """How many leading full blocks of `prompt` the local PrefixCache
    holds right now. Caller must hold engine._kv_mutex."""
    n_full = len(prompt) // engine.block_size
    if n_full == 0:
        return 0
    n_tok, ids = engine._prefix.lookup(
        list(prompt), max_tokens=n_full * engine.block_size)
    for bid in ids:
        engine._pool.decref(bid)
    return len(ids)


def export_prefix(engine, prompt: Sequence[int],
                  run_prefill: bool = True) -> dict:
    """Pack the full-block prefix of `prompt` into a kv_wire shipment.

    If the prefix is not resident and `run_prefill` is true, this runs
    one generation step through the engine (chunked prefill registers
    every full prompt block in the PrefixCache before the first token
    is returned) — the prefill worker's actual job.
    """
    _require_paged(engine)
    prompt = [int(t) for t in prompt]
    n_full = len(prompt) // engine.block_size
    if n_full == 0:
        return kv_wire.pack_blocks(
            engine.scope, engine.step.cache_names, [], [],
            engine.block_size)
    with engine._kv_mutex:
        resident = _resident_depth(engine, prompt)
    if resident < n_full:
        if not run_prefill:
            raise ValueError(
                f"prefix not resident ({resident}/{n_full} blocks) and "
                "run_prefill=False")
        # One token is enough: _register_prefix runs at first-token
        # time, before generate() returns.
        engine.generate(prompt, 1)
    with engine._kv_mutex:
        n_tok, ids = engine._prefix.lookup(
            prompt, max_tokens=n_full * engine.block_size)
        try:
            hashes = PrefixCache.chunk_hashes(
                prompt[:len(ids) * engine.block_size], engine.block_size)
            payload = kv_wire.pack_blocks(
                engine.scope, engine.step.cache_names, ids, hashes,
                engine.block_size)
        finally:
            for bid in ids:
                engine._pool.decref(bid)
        engine._set_block_gauges()
    STAT_ADD("serving.kv_xfer_exports")
    return payload


def adopt_prefix(engine, payload: dict) -> dict:
    """Unpack a shipment into the engine's BlockPool + PrefixCache.

    Blocks whose chain hash is already cached locally are skipped
    (duplicate); new blocks go through the normal alloc → insert
    (cache incref) path so they are owned by the cache at refcount 1
    and evictable under pressure like any other prefix.  Pool
    exhaustion stops adoption early — a leading sub-chain is still a
    valid prefix, the decode worker just re-prefills the tail.
    """
    _require_paged(engine)
    ship = payload if isinstance(payload, kv_wire.KVShipment) \
        else kv_wire.unpack_blocks(payload)
    if ship.block_size != engine.block_size:
        raise ValueError(
            f"shipment block_size {ship.block_size} != engine "
            f"block_size {engine.block_size}")
    names = engine.step.cache_names
    if 2 * len(ship.layers) != len(names):
        raise ValueError(
            f"shipment has {len(ship.layers)} layers, engine has "
            f"{len(names) // 2}")
    adopted = 0
    dup = 0
    with engine._kv_mutex:
        if ship.n_blocks and ship.layers:
            pool0 = np.asarray(engine.scope.get(names[0]))
            if ship.dtype != pool0.dtype or \
                    tuple(ship.shape[1:]) != tuple(pool0.shape[1:]):
                raise ValueError(
                    f"shipment rows {ship.dtype}{list(ship.shape[1:])} "
                    f"!= pool rows {pool0.dtype}"
                    f"{list(pool0.shape[1:])}")
        pools = None
        for j, h in enumerate(ship.chain_hashes):
            if h in engine._prefix._entries:
                dup += 1
                engine._prefix._entries.move_to_end(h)
                continue
            bid = engine._alloc_block()
            if bid is None:
                break  # pool exhausted; keep the leading sub-chain
            if pools is None:
                pools = [np.array(np.asarray(engine.scope.get(n)))
                         for n in names]
            for li, (karr, varr) in enumerate(ship.layers):
                pools[2 * li][bid] = karr[j]
                pools[2 * li + 1][bid] = varr[j]
            engine._prefix.insert(h, bid)   # cache takes its ref (-> 2)
            engine._pool.decref(bid)        # drop ours (-> 1, cache-held)
            adopted += 1
        if pools is not None:
            for n, arr in zip(names, pools):
                engine.scope.set(n, arr)
        resident = 0
        for h in ship.chain_hashes:
            if h in engine._prefix._entries:
                resident += 1
            else:
                break
        engine._set_block_gauges()
    STAT_ADD("serving.kv_xfer_adopted_blocks", adopted)
    if dup:
        STAT_ADD("serving.kv_xfer_dup_blocks", dup)
    return {"adopted": adopted, "duplicate": dup, "resident": resident,
            "blocks": ship.n_blocks, "n_tokens": ship.n_tokens,
            "block_size": ship.block_size}


class FleetPrefixStore:
    """Router-side content-addressed registry: chain hash -> replica
    names that hold the block. LRU-bounded; thread-safe."""

    def __init__(self, max_entries: Optional[int] = None):
        self._max = int(FLAGS.disagg_fleet_prefix_max
                        if max_entries is None else max_entries)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Set[str]]" = OrderedDict()
        self._block_size: Optional[int] = None

    @property
    def block_size(self) -> Optional[int]:
        return self._block_size

    def learn_block_size(self, block_size: int):
        if block_size and block_size > 0:
            self._block_size = int(block_size)

    def register(self, hashes: Iterable[str], owner: str):
        with self._lock:
            for h in hashes:
                owners = self._entries.get(h)
                if owners is None:
                    owners = set()
                    self._entries[h] = owners
                owners.add(owner)
                self._entries.move_to_end(h)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)

    def owned_depth(self, hashes: Sequence[str], owner: str) -> int:
        """Leading count of `hashes` registered to `owner`."""
        with self._lock:
            depth = 0
            for h in hashes:
                owners = self._entries.get(h)
                if owners is None or owner not in owners:
                    break
                depth += 1
            return depth

    def chain_owner(self, hashes: Sequence[str],
                    exclude: Iterable[str] = ()) -> Optional[str]:
        """A replica (not in `exclude`) that owns the WHOLE leading
        chain, or None."""
        if not hashes:
            return None
        skip = set(exclude)
        with self._lock:
            candidates: Optional[Set[str]] = None
            for h in hashes:
                owners = self._entries.get(h)
                if not owners:
                    return None
                live = {o for o in owners if o not in skip}
                candidates = live if candidates is None \
                    else candidates & live
                if not candidates:
                    return None
            return sorted(candidates)[0] if candidates else None

    def drop_owner(self, owner: str):
        """Forget every block owned by `owner` (replica removed/died)."""
        with self._lock:
            dead = []
            for h, owners in self._entries.items():
                owners.discard(owner)
                if not owners:
                    dead.append(h)
            for h in dead:
                del self._entries[h]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries),
                    "owners": len({o for owners in self._entries.values()
                                   for o in owners}),
                    "block_size": self._block_size or 0}

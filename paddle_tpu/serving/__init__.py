"""Serving subsystem: dynamic batching + shape-bucketed warmup + HTTP.

Reference: the reference framework's dedicated inference/serving layer
(predictor pools, request queues, service front ends). The TPU-native
redesign centers on XLA's whole-program, shape-specialized compilation:
naive serving recompiles on every novel (batch, seq) shape, so the
engine quantizes all traffic onto a fixed bucket ladder
(`BucketLadder`), coalesces concurrent requests into padded batches
(`DynamicBatcher`), and precompiles every ladder cell before accepting
traffic (`ServingEngine.warmup`). A stdlib HTTP front end
(`serving.http.serve`) exposes /v1/predict, /v1/generate, /healthz and
/metrics.

Autoregressive LLM traffic goes through `GenerationEngine`
(serving/generation.py): Orca-style continuous batching over the
multi-slot KV-cache decode step of models/gpt.py — requests join and
leave a running decode batch between steps, with the whole serving
lifetime covered by ONE compiled executable.

Quick start::

    from paddle_tpu.serving import EngineConfig, ServingEngine, serve
    cfg = EngineConfig(model_dir, max_batch_size=8, seq_buckets=(32, 64))
    srv = serve(ServingEngine(cfg), port=8000)   # warms up, then binds

See docs/serving.md for the architecture and the full stat inventory.
"""
from .batcher import (BucketLadder, DeadlineExceededError,  # noqa: F401
                      DynamicBatcher, EngineClosedError, OverloadedError,
                      QueueFullError, ServingError)
from .engine import EngineConfig, ServingEngine  # noqa: F401
from .generation import (GenerationEngine, GenerationRequest,  # noqa: F401
                         SlotManager)
from .http import ServingHTTPServer, serve  # noqa: F401
from .kv_blocks import (BlockPool, PrefixCache,  # noqa: F401
                        blocks_for_tokens)
from .disagg import (FleetPrefixStore, adopt_prefix,  # noqa: F401
                     export_prefix)
from .kv_wire import (KVShipment, pack_blocks,  # noqa: F401
                      unpack_blocks)
from .router import Replica, Router, RouterHTTP  # noqa: F401
from .spec_decode import NgramDrafter, update_spec_k  # noqa: F401

__all__ = ["BucketLadder", "DynamicBatcher", "EngineConfig",
           "ServingEngine", "ServingHTTPServer", "serve", "ServingError",
           "QueueFullError", "DeadlineExceededError", "EngineClosedError",
           "OverloadedError", "GenerationEngine", "GenerationRequest",
           "SlotManager", "BlockPool", "PrefixCache",
           "blocks_for_tokens", "Replica", "Router", "RouterHTTP",
           "NgramDrafter", "update_spec_k", "FleetPrefixStore",
           "export_prefix", "adopt_prefix", "KVShipment",
           "pack_blocks", "unpack_blocks"]

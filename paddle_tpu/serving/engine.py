"""Serving engine: warmed, batched inference over AnalysisPredictor.

Reference: the reference framework's inference layer wraps the predictor
in a multi-threaded service with a predictor pool; here the engine is
one (or a few) worker threads draining a `DynamicBatcher`, because on
TPU the device-side concurrency lives inside the single XLA executable —
what the host must provide is SHAPE discipline. `EngineConfig` pins a
bucket ladder, `warmup()` runs one dummy batch per (batch-bucket x
seq-bucket) cell so every reachable shape is already in the Executor's
executable cache before traffic arrives, and the worker only ever feeds
ladder shapes, so steady-state serving triggers zero compiles.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import goodput as _goodput
from .. import trace
from ..monitor import STAT_ADD, STAT_OBSERVE
from ..resilience.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from ..resilience.faults import TransientFault
from ..resilience.faults import injector as _fault_injector
from ..resilience.retry import RetryPolicy, is_transient
from .batcher import (BATCH_BUCKETS_HIST, BucketLadder, DynamicBatcher,
                      EngineClosedError, FRACTION_BUCKETS,
                      OverloadedError)

__all__ = ["EngineConfig", "ServingEngine"]


class EngineConfig:
    """Knobs of one serving engine. Defaults come from the FLAGS_serving_*
    registry so deployments can tune an unmodified entry point from the
    environment (the flags-as-env contract of core/flags.py)."""

    def __init__(self, model_dir: Optional[str] = None,
                 max_batch_size: Optional[int] = None,
                 max_wait_us: Optional[int] = None,
                 queue_capacity: Optional[int] = None,
                 default_timeout_ms: Optional[float] = None,
                 batch_buckets: Optional[Sequence[int]] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 seq_axis: int = 1,
                 feed_spec: Optional[Dict[str, Tuple[tuple, str]]] = None,
                 warmup: bool = True,
                 num_workers: int = 1,
                 http_port: Optional[int] = None):
        from ..core.flags import FLAGS
        self.model_dir = model_dir
        self.max_batch_size = int(max_batch_size
                                  if max_batch_size is not None
                                  else FLAGS.serving_max_batch_size)
        self.max_wait_us = int(max_wait_us if max_wait_us is not None
                               else FLAGS.serving_max_wait_us)
        self.queue_capacity = int(queue_capacity
                                  if queue_capacity is not None
                                  else FLAGS.serving_queue_capacity)
        self.default_timeout_ms = float(
            default_timeout_ms if default_timeout_ms is not None
            else FLAGS.serving_default_timeout_ms)
        if batch_buckets is None:
            # powers of two up to max_batch_size (always including it)
            batch_buckets = sorted({1 << i for i in
                                    range(self.max_batch_size.bit_length())
                                    if 1 << i <= self.max_batch_size}
                                   | {self.max_batch_size})
        self.batch_buckets = tuple(batch_buckets)
        self.seq_buckets = tuple(seq_buckets) if seq_buckets else None
        self.seq_axis = seq_axis
        # feed_spec: {name: (shape_per_example, dtype)} with None dims for
        # the seq axis; inferred from the program when omitted
        self.feed_spec = feed_spec
        self.warmup = warmup
        self.num_workers = max(1, int(num_workers))
        self.http_port = int(http_port if http_port is not None
                             else FLAGS.serving_http_port)

    def ladder(self) -> BucketLadder:
        return BucketLadder(self.batch_buckets, self.seq_buckets,
                            self.seq_axis)


class ServingEngine:
    """Batched, warmed, instrumented inference service.

    Lifecycle: construct (loads the model), `start()` (warmup + worker
    threads), `submit`/`predict` from any thread, `stop(drain=True)`.
    """

    def __init__(self, config: EngineConfig, predictor=None):
        from ..inference import AnalysisConfig, create_paddle_predictor
        if predictor is None:
            if not config.model_dir:
                raise ValueError(
                    "EngineConfig.model_dir or an explicit predictor is "
                    "required")
            predictor = create_paddle_predictor(
                AnalysisConfig(config.model_dir))
        self.config = config
        self.predictor = predictor
        self._ladder = config.ladder()
        self._batcher = DynamicBatcher(
            self._ladder, config.max_batch_size, config.max_wait_us,
            config.queue_capacity, config.default_timeout_ms)
        self._workers: List[threading.Thread] = []
        # Predictor clones share program/scope/compile-cache but the
        # donated-state execution path is not reentrant: serialize the
        # actual device dispatch. With one worker the lock is free;
        # extra workers still overlap host-side pad/concat/scatter.
        self._infer_lock = threading.Lock()
        self._ready = threading.Event()
        self._stopping = False
        self._warmed_shapes: List[tuple] = []
        # resilience: transient batch failures retry invisibly; repeated
        # failures trip the breaker and submissions shed with
        # OverloadedError until a half-open probe succeeds
        self._breaker = CircuitBreaker(name="serving")
        self._retry = RetryPolicy()
        self._state = "warming"  # warming -> ready -> stopped

    # -- shape spec ------------------------------------------------------
    def _feed_spec(self) -> Dict[str, Tuple[tuple, str]]:
        """{feed name: (per-example shape with None at the seq axis,
        numpy dtype str)} — from EngineConfig.feed_spec or inferred from
        the loaded program's data vars (-1 dims: axis 0 is batch; the
        configured seq axis is a seq bucket; anything else needs an
        explicit spec)."""
        if self.config.feed_spec is not None:
            return dict(self.config.feed_spec)
        from ..core.dtypes import as_np_dtype
        block = self.predictor.program().global_block()
        spec = {}
        for name in self.predictor.get_input_names():
            var = block.var(name)
            shape = list(var.shape or ())
            if not shape:
                raise ValueError(
                    f"feed {name!r} has no static shape; pass "
                    f"EngineConfig.feed_spec")
            per_example = []
            for axis, dim in enumerate(shape[1:], start=1):
                if dim == -1:
                    if axis == self.config.seq_axis \
                            and self.config.seq_buckets:
                        per_example.append(None)
                    else:
                        raise ValueError(
                            f"feed {name!r} axis {axis} is dynamic but "
                            f"not the configured seq axis; pass "
                            f"EngineConfig.feed_spec")
                else:
                    per_example.append(int(dim))
            spec[name] = (tuple(per_example),
                          str(np.dtype(as_np_dtype(var.dtype))))
        return spec

    def warmup_shapes(self) -> List[tuple]:
        """Every (batch_bucket, seq_bucket) cell of the ladder
        (seq_bucket None when the ladder has no seq dimension)."""
        seqs = self.config.seq_buckets or (None,)
        return list(itertools.product(self.config.batch_buckets, seqs))

    def warmup(self) -> int:
        """Run one dummy batch per ladder cell so every reachable shape
        lands in the Executor's executable cache before traffic.
        Returns the number of shapes warmed."""
        # Static verification BEFORE spending any compiles
        # (FLAGS_program_verify): in error mode a malformed model is
        # rejected at load — cache_stats() still shows zero misses —
        # instead of failing mid-traffic after minutes of warmup.
        from ..analysis import verify_gate
        verify_gate(self.predictor.program(),
                    feed_names=self.predictor.get_input_names(),
                    fetch_names=self.predictor.get_output_names(),
                    where="serving.warmup")
        # Graph-optimization pipeline, ONCE for the whole ladder
        # (FLAGS_graph_opt_level): the pipeline memoizes per
        # (fingerprint, level, feeds, fetches), so priming it here
        # means every ladder cell below — and all steady-state traffic
        # — compiles the optimized program without re-running a single
        # pass per cell.
        from ..analysis import optimize_gate
        opt_prog, _ = optimize_gate(
            self.predictor.program(),
            feed_names=self.predictor.get_input_names(),
            fetch_names=self.predictor.get_output_names(),
            where="serving.warmup")
        spec = self._feed_spec()
        shapes = self.warmup_shapes()
        # Static memory gate over EVERY ladder cell before the first
        # compile (FLAGS_memory_gate): the warmup budget check is the
        # max over cells, so one oversized (batch, seq) corner rejects
        # the whole ladder with cache_stats() still at zero misses —
        # instead of OOMing after the smaller cells already compiled.
        # Analyzes the optimized program (level-2 buffer reuse counts);
        # the per-cell plans are memoized, so the executor's own gate
        # hits the same entries during the warm loop below.
        from ..analysis import memory_gate, sharding_gate
        for bb, sb in shapes:
            cell = {}
            for name, (per_example, dtype) in spec.items():
                dims = [bb] + [sb if d is None else d
                               for d in per_example]
                if any(d is None for d in dims):
                    raise ValueError(
                        f"feed {name!r} has a seq dim but the ladder "
                        f"has no seq_buckets")
                cell[name] = (tuple(dims), dtype)
            memory_gate(opt_prog, feed_shapes=cell,
                        fetch_names=self.predictor.get_output_names(),
                        where="serving.warmup")
            # Static sharding gate per cell (FLAGS_sharding_verify):
            # engages only when FLAGS_sharded_mesh puts a layout in
            # scope; a layout-inconsistent model raises PTV060 here,
            # before the ladder spends its first compile.
            sharding_gate(opt_prog, feed_shapes=cell,
                          fetch_names=self.predictor.get_output_names(),
                          where="serving.warmup")
        for bb, sb in shapes:
            feed = {}
            for name, (per_example, dtype) in spec.items():
                dims = [bb] + [sb if d is None else d
                               for d in per_example]
                if any(d is None for d in dims):
                    raise ValueError(
                        f"feed {name!r} has a seq dim but the ladder "
                        f"has no seq_buckets")
                feed[name] = np.zeros(dims, dtype=dtype)
            t0 = time.perf_counter()
            with self._infer_lock:
                self.predictor.run_dict(feed)
            STAT_OBSERVE("serving.warmup_seconds",
                         time.perf_counter() - t0)
            STAT_ADD("serving.warmup_shapes")
            self._warmed_shapes.append((bb, sb))
        return len(shapes)

    # -- lifecycle -------------------------------------------------------
    def start(self):
        """Warm the ladder (unless config.warmup is off), then start the
        worker thread(s) and mark the engine ready."""
        if self._workers:
            return self
        self._state = "warming"
        if self.config.warmup:
            self.warmup()
        self._stopping = False
        for i in range(self.config.num_workers):
            w = threading.Thread(target=self._worker_loop,
                                 name=f"ptn-serving-worker-{i}",
                                 daemon=True)
            w.start()
            self._workers.append(w)
        self._state = "ready"
        self._ready.set()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0):
        """Shut down: reject new submissions, then either finish queued
        requests (drain=True) or fail them, and join the workers."""
        self._ready.clear()
        self._state = "stopped"
        self._stopping = True
        self._batcher.close(drain=drain)
        for w in self._workers:
            w.join(timeout)
        self._workers = []

    @property
    def ready(self) -> bool:
        return self._ready.is_set()

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    def health(self) -> Dict[str, object]:
        """Load-balancer health view: ``state`` is one of warming /
        ready / degraded (half-open probing) / open (shedding) /
        stopped, plus the raw breaker state and the Retry-After
        seconds while open. /healthz serves this."""
        if self._state != "ready":
            return {"state": self._state, "breaker": self._breaker.state,
                    "retry_after_s": 0.0}
        b = self._breaker.state
        state = {OPEN: "open", HALF_OPEN: "degraded",
                 CLOSED: "ready"}[b]
        return {"state": state, "breaker": b,
                "retry_after_s": self._breaker.retry_after_s()}

    # -- request path ----------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None):
        """Enqueue; returns a response handle (`.result()` blocks).
        Raises OverloadedError while the circuit breaker is OPEN
        (load shedding: don't queue work the backend cannot do)."""
        if not self._breaker.allow():
            raise OverloadedError(
                "serving backend is unhealthy (circuit breaker open)",
                retry_after_s=self._breaker.retry_after_s())
        return self._batcher.submit(feed, timeout_ms=timeout_ms)

    def predict(self, feed: Dict[str, np.ndarray],
                timeout_ms: Optional[float] = None) -> List[np.ndarray]:
        """Blocking submit+wait: the outputs sliced to this request's
        rows, in `get_output_names()` order."""
        return self.submit(feed, timeout_ms=timeout_ms).result()

    def load(self) -> int:
        """Instantaneous queue depth (rows pending in the batcher) —
        what the router's least-loaded dispatch compares."""
        return self._batcher.pending_rows()

    def output_names(self) -> List[str]:
        return self.predictor.get_output_names()

    def cache_stats(self) -> Dict[str, int]:
        """The predictor-executor's per-instance executable-cache
        counters. With warmup on and traffic confined to the ladder,
        `misses` must not move after `start()` returns — the acceptance
        check tools/serving_loadgen.py --check-compiles runs."""
        return self.predictor._exe.cache_stats()

    # -- worker ----------------------------------------------------------
    def _execute(self, feed):
        """One dispatch attempt: fault hook, device run, output
        hygiene. A non-finite float output (FLAGS_serving_nan_guard)
        raises TransientFault — the executor's device state is
        untouched by a host-side corruption, so re-running the same
        feed is a valid cure and the RetryPolicy wrapping this call
        turns a glitched batch into a clean answer instead of a wrong
        one."""
        inj = _fault_injector()
        if inj is not None:
            inj.pre_step("serving")
        with self._infer_lock:
            outputs = self.predictor.run_dict(feed)
        if inj is not None:
            outputs = list(outputs)
            inj.corrupt_fetches("serving", outputs)
        from ..core.flags import FLAGS
        if FLAGS.serving_nan_guard:
            for o in outputs:
                o = np.asarray(o)
                if np.issubdtype(o.dtype, np.floating) and o.size \
                        and not np.all(np.isfinite(o)):
                    STAT_ADD("resilience.nan_batches_retried")
                    raise TransientFault(
                        "non-finite value in batch outputs")
        return outputs

    def _worker_loop(self):
        while True:
            # serving goodput: time blocked in next_batch (empty queue or
            # batching window) is idle; everything from batch receipt to
            # scatter is busy. Pad waste = execute time x the ladder's
            # padded-row fraction (the slack baked into the batch shape).
            t_wait0 = time.perf_counter()
            batch = self._batcher.next_batch(timeout=0.1)
            _goodput.serving_idle(time.perf_counter() - t_wait0)
            if batch is None:
                if self._stopping and self._batcher.pending_rows() == 0:
                    return
                continue
            t_busy0 = time.perf_counter()
            try:
                # One span per dispatched batch. It cannot PARENT the
                # member request spans (they live in N different
                # traces), so it links them instead; being contextvar-
                # current, the executor's feed/dispatch/fetch sub-spans
                # attach under it.
                bspan = trace.start_span(
                    "serving.batch", attrs={"rows": batch.rows})
                if bspan is not None:
                    for r in batch.requests:
                        bspan.add_link(r.span)
                try:
                    with trace.use_span(bspan):
                        feed, bucket, waste = batch.build_feed(
                            self._ladder)
                        t_exec0 = time.perf_counter()
                        outputs = self._retry.call(self._execute, feed)
                        _goodput.serving_pad_waste(
                            waste * (time.perf_counter() - t_exec0))
                except Exception as e:  # noqa: BLE001 — close the batch
                    # trace, then let the existing handler fail the batch
                    trace.finish_trace(bspan,
                                       error=f"{type(e).__name__}: {e}",
                                       record_latency=False)
                    raise
                trace.finish_trace(bspan, record_latency=False)
                STAT_ADD("serving.batches")
                STAT_OBSERVE("serving.batch_size", batch.rows,
                             buckets=BATCH_BUCKETS_HIST)
                STAT_OBSERVE("serving.pad_waste_frac", waste,
                             buckets=FRACTION_BUCKETS)
                batch.scatter(outputs)
                self._breaker.record_success()
                _goodput.serving_busy(time.perf_counter() - t_busy0)
            except Exception as e:  # noqa: BLE001 — a poison batch must
                # fail ITS requests, not kill the worker thread
                if is_transient(e):
                    # exhausted-retry transients mean the backend is
                    # sick; poison (bad request) is the client's fault
                    # and must not trip the breaker
                    self._breaker.record_failure()
                batch.fail(e if isinstance(e, EngineClosedError)
                           else RuntimeError(f"batch execution failed: "
                                             f"{e!r}"))

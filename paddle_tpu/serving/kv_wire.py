"""KV wire format: serialize filled block-table rows for cross-process
transfer.

A *shipment* carries, for one request prefix, the per-layer paged-KV
pool rows that hold its already-prefilled tokens, plus the content
chain hashes (`PrefixCache.chunk_hashes`) that name them and the
start-position metadata a decode worker needs to resume.  Payloads are
base64 of the raw pool bytes — `np.tobytes`/`np.frombuffer` round-trip
is byte-exact for fp32 and bf16 alike, so the adopting worker decodes
from tensors bit-identical to the ones the prefill worker computed.

The format rides the existing serving/http.py JSON protocol (one JSON
object per POST body); no new transport is introduced.
"""
from __future__ import annotations

import base64
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

WIRE_VERSION = 1


def _resolve_dtype(name: str) -> np.dtype:
    """Resolve a dtype name from the wire, including bfloat16 (which
    numpy alone does not know — jax ships ml_dtypes, so gate on it)."""
    if name == "bfloat16":
        try:
            import ml_dtypes  # noqa: F401  (registers bfloat16)
            return np.dtype(ml_dtypes.bfloat16)
        except ImportError as e:  # pragma: no cover - env without jax
            raise ValueError(
                "shipment dtype bfloat16 needs ml_dtypes "
                "(bundled with jax)") from e
    return np.dtype(name)


def _dtype_name(dt: np.dtype) -> str:
    return dt.name


@dataclass
class KVShipment:
    """Decoded wire payload: per-layer (k, v) row stacks of shape
    [n_blocks, block_size, n_heads, head_dim]."""
    version: int
    block_size: int
    n_tokens: int
    dtype: np.dtype
    shape: Tuple[int, int, int, int]
    chain_hashes: List[str]
    layers: List[Tuple[np.ndarray, np.ndarray]]

    @property
    def n_blocks(self) -> int:
        return self.shape[0]


def pack_blocks(scope, cache_names: Sequence[str],
                block_ids: Sequence[int],
                chain_hashes: Sequence[str],
                block_size: int) -> dict:
    """Serialize pool rows `block_ids` from every paged KV pool in
    `cache_names` (alternating k, v per layer) into a JSON-safe dict.

    `chain_hashes[i]` must be the content hash of the tokens stored in
    `block_ids[i]`; the adopting side keys its PrefixCache on them.
    """
    if len(cache_names) % 2 != 0:
        raise ValueError(
            f"cache_names must alternate k/v pools, got {len(cache_names)}")
    if len(block_ids) != len(chain_hashes):
        raise ValueError(
            f"{len(block_ids)} block ids vs {len(chain_hashes)} hashes")
    ids = list(int(b) for b in block_ids)
    layers = []
    shape = None
    dtype = None
    for name in cache_names:
        pool = np.asarray(scope.get(name))
        rows = np.ascontiguousarray(pool[ids])
        if shape is None:
            shape = rows.shape
            dtype = rows.dtype
        layers.append(base64.b64encode(rows.tobytes()).decode("ascii"))
    if shape is None:
        shape = (len(ids), block_size, 0, 0)
        dtype = np.dtype("float32")
    payload = {
        "kind": "kv_shipment",
        "version": WIRE_VERSION,
        "block_size": int(block_size),
        "n_blocks": len(ids),
        "n_tokens": len(ids) * int(block_size),
        "dtype": _dtype_name(dtype),
        "shape": [int(d) for d in shape],
        "chain_hashes": list(chain_hashes),
        "layers": [{"k": layers[i], "v": layers[i + 1]}
                   for i in range(0, len(layers), 2)],
    }
    return payload


def unpack_blocks(payload: dict) -> KVShipment:
    """Decode a `pack_blocks` dict back into numpy row stacks.

    Raises ValueError on malformed payloads (wrong kind/version,
    truncated buffers) so http.py can map it to a 400.
    """
    if payload.get("kind") != "kv_shipment":
        raise ValueError("not a kv_shipment payload")
    if payload.get("version") != WIRE_VERSION:
        raise ValueError(
            f"kv_shipment version {payload.get('version')!r}, "
            f"expected {WIRE_VERSION}")
    shape = tuple(int(d) for d in payload["shape"])
    if len(shape) != 4:
        raise ValueError(f"bad shipment shape {shape}")
    dtype = _resolve_dtype(str(payload["dtype"]))
    hashes = [str(h) for h in payload["chain_hashes"]]
    if len(hashes) != shape[0]:
        raise ValueError(
            f"{len(hashes)} chain hashes for {shape[0]} blocks")
    want = int(np.prod(shape)) * dtype.itemsize
    layers: List[Tuple[np.ndarray, np.ndarray]] = []
    for layer in payload["layers"]:
        pair = []
        for key in ("k", "v"):
            raw = base64.b64decode(layer[key])
            if len(raw) != want:
                raise ValueError(
                    f"layer {key} buffer is {len(raw)} bytes, "
                    f"expected {want}")
            pair.append(np.frombuffer(raw, dtype=dtype).reshape(shape))
        layers.append((pair[0], pair[1]))
    return KVShipment(
        version=WIRE_VERSION,
        block_size=int(payload["block_size"]),
        n_tokens=int(payload["n_tokens"]),
        dtype=dtype,
        shape=shape,  # type: ignore[arg-type]
        chain_hashes=hashes,
        layers=layers)


def payload_bytes(payload: dict) -> int:
    """Raw KV bytes carried by a packed shipment (excludes base64 and
    JSON overhead): n_layers * 2 pools * prod(shape) * itemsize."""
    shape = [int(d) for d in payload.get("shape", ())]
    if len(shape) != 4:
        return 0
    dtype = _resolve_dtype(str(payload.get("dtype", "float32")))
    per_pool = int(np.prod(shape)) * dtype.itemsize
    return per_pool * 2 * len(payload.get("layers", ()))

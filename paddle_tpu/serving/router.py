"""Multi-replica serving router: the front tier over N engine replicas.

One process, one mesh, one breaker was the story through PR 8 — a
NaN-poisoned worker or a SIGTERM took the whole service down. This
module turns that single server into a fleet:

- **Least-loaded dispatch.** Every `Replica` exposes its instantaneous
  load (ServingEngine batcher rows + GenerationEngine queued/active
  slots + the router's own in-flight count); `POST /v1/predict` and
  `/v1/generate` go to the healthy replica with the smallest load.
- **Health gating.** An active probe loop polls each replica
  (`/healthz` for ``url=`` replicas, `engine.health()` in-process) on
  FLAGS_router_probe_interval_s, and a per-replica `CircuitBreaker`
  does passive failure accounting on the dispatch path — either signal
  routes traffic around a sick replica.
- **Failover.** A retryable dispatch failure (replica death, 503 shed,
  connection reset) re-dispatches the request to a different healthy
  replica, bounded by FLAGS_router_redispatch_budget and honoring the
  replica's ``Retry-After`` backoff. Requests here are idempotent
  (predict is pure; generation is seeded), so a re-dispatch can never
  produce a different answer. Deadline expiries and malformed requests
  are NOT retried.
- **Session affinity.** `generate(..., session=)` pins a session to
  one replica while it stays healthy, so its KV prefix cache keeps
  paying; affinity breaks (and re-pins) the moment the pinned replica
  leaves the healthy set.
- **Zero-downtime hot-swap.** `hot_swap(old, standby)` warms the
  standby through the full bucket ladder while the old replica keeps
  serving, refuses to flip if the standby would compile post-warmup,
  atomically swaps the routing table, then drains the old replica to
  zero in-flight (bounded by FLAGS_router_drain_timeout_s) before
  stopping it.
- **Preemption-aware membership.** `preempt(name)` (wired to SIGTERM
  via `install_sigterm`, chaining any previous handler like
  resilience/trainer_guard.py) deregisters a replica without killing
  its in-flight work; `resume(name)` re-registers it. The router sheds
  load (OverloadedError → 503 + Retry-After) only when *every* replica
  is out.

Spans: each dispatch attempt runs under a ``router.dispatch`` span
(child of the caller's request span). For ``url=`` replicas the
traceparent of that span crosses the hop, so the replica's
``http.request`` span parents under it and one trace covers both tiers.
"""
from __future__ import annotations

import json
import signal
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from .. import trace
from ..core.flags import FLAGS
from ..monitor import STAT_ADD, STAT_OBSERVE, STAT_SET, flight_record
from ..resilience.breaker import CircuitBreaker
from .batcher import (DeadlineExceededError, EngineClosedError,
                      OverloadedError, QueueFullError)

__all__ = ["Replica", "Router", "RouterHTTP"]

# health() states that keep a replica in the routing table
_ROUTABLE_STATES = ("ok", "ready", "degraded")

# dispatch failures that justify trying another replica (the request
# never ran, or the backend refused/lost it before answering)
_RETRYABLE = (OverloadedError, QueueFullError, EngineClosedError,
              ConnectionError)


class Replica:
    """One backend the router can dispatch to: either in-process
    engines (``engine=`` / ``gen_engine=``, called directly) or a
    remote replica server (``url=``, spoken to over the same JSON
    protocol serving/http.py serves).

    The router only reads/writes a replica through this surface:
    `load()`, `health()`, `predict()`, `generate()`, drain/stop, plus
    the passive-accounting breaker."""

    def __init__(self, name: str, engine=None, gen_engine=None,
                 url: Optional[str] = None, version: str = "v1",
                 failure_threshold: Optional[int] = None,
                 role: str = "unified"):
        if url is None and engine is None and gen_engine is None:
            raise ValueError(f"replica {name!r} needs engine, "
                             "gen_engine, or url")
        if url is not None and (engine is not None
                                or gen_engine is not None):
            raise ValueError(f"replica {name!r}: url= and in-process "
                             "engines are mutually exclusive")
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"replica {name!r}: role must be unified, prefill, or "
                f"decode, got {role!r}")
        self.name = name
        self.engine = engine
        self.gen_engine = gen_engine
        self.url = url.rstrip("/") if url else None
        self.version = version
        self.role = role
        self.registered = True
        self.healthy = True          # last probe verdict
        self.backoff_until = 0.0     # monotonic; Retry-After honor
        self.breaker = CircuitBreaker(
            failure_threshold=(
                failure_threshold if failure_threshold is not None
                else FLAGS.router_failure_threshold),
            name=f"router.{name}")
        self._inflight = 0
        self._cv = threading.Condition()
        self._warm_misses: Optional[int] = None

    # -- lifecycle -------------------------------------------------------

    def start(self, timeout_s: float = 120.0):
        """Warm the replica to readiness: in-process engines run their
        full warmup ladder; a url replica is polled until /healthz
        leaves ``warming``."""
        if self.url is not None:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                h = self.health()
                if h["state"] in _ROUTABLE_STATES:
                    return
                time.sleep(0.05)
            raise TimeoutError(
                f"replica {self.name!r} at {self.url} did not become "
                f"ready within {timeout_s}s")
        if self.engine is not None:
            self.engine.start()
            self._warm_misses = self.engine.cache_stats()["misses"]
        if self.gen_engine is not None:
            self.gen_engine.start()

    def stop(self, drain: bool = True, timeout: float = 30.0):
        if self.engine is not None:
            self.engine.stop(drain=drain, timeout=timeout)
        if self.gen_engine is not None:
            self.gen_engine.stop(drain=drain, timeout=timeout)

    def post_warmup_compiles(self) -> int:
        """Compiles since start() across both engines — must be 0 for
        a standby to be allowed into the routing table (hot-swap's
        no-compile-storm gate)."""
        n = 0
        if self.gen_engine is not None:
            n += self.gen_engine.post_warmup_compiles()
        if self.engine is not None and self._warm_misses is not None:
            n += self.engine.cache_stats()["misses"] - self._warm_misses
        return n

    # -- routing inputs --------------------------------------------------

    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    def load(self) -> float:
        """Dispatch metric: backend queue depth + requests this router
        already has in flight on the replica (covers the window before
        the backend's own gauges move)."""
        n = float(self.inflight())
        if self.url is not None:
            return n
        if self.engine is not None:
            n += self.engine.load()
        if self.gen_engine is not None:
            n += self.gen_engine.load()
        return n

    def health(self) -> dict:
        """Worst-state-wins across the replica's engines, same ranking
        /healthz uses; url replicas answer their actual /healthz."""
        if self.url is not None:
            return self._remote_health()
        from .http import _STATE_RANK
        worst, retry_after = "ready", 0.0
        for e in (self.engine, self.gen_engine):
            if e is None:
                continue
            h = e.health()
            if _STATE_RANK.get(h["state"], 4) > \
                    _STATE_RANK.get(worst, 4):
                worst = h["state"]
            retry_after = max(retry_after,
                              h.get("retry_after_s") or 0.0)
        return {"state": "ok" if worst == "ready" else worst,
                "retry_after_s": retry_after}

    def _remote_health(self) -> dict:
        try:
            req = urllib.request.Request(self.url + "/healthz")
            with urllib.request.urlopen(req, timeout=2.0) as r:
                body = json.loads(r.read() or b"{}")
                return {"state": body.get("state", "ok"),
                        "retry_after_s": 0.0}
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except Exception:
                body = {}
            ra = e.headers.get("Retry-After") if e.headers else None
            return {"state": body.get("state", "open"),
                    "retry_after_s": float(ra) if ra else 0.0}
        except Exception:
            return {"state": "stopped", "retry_after_s": 0.0}

    # -- dispatch --------------------------------------------------------

    def _track(self):
        return _Inflight(self)

    def predict(self, feed: Dict[str, np.ndarray],
                timeout_ms: Optional[float] = None
                ) -> Dict[str, np.ndarray]:
        with self._track():
            if self.url is not None:
                payload = {"inputs": {k: np.asarray(v).tolist()
                                      for k, v in feed.items()}}
                if timeout_ms is not None:
                    payload["timeout_ms"] = timeout_ms
                body = self._post("/v1/predict", payload, timeout_ms)
                return {k: np.asarray(v)
                        for k, v in body["outputs"].items()}
            if self.engine is None:
                raise ValueError(
                    f"replica {self.name!r} has no predict engine")
            outs = self.engine.predict(feed, timeout_ms=timeout_ms)
            return dict(zip(self.engine.output_names(), outs))

    def generate(self, payload: dict) -> dict:
        with self._track():
            if self.url is not None:
                return self._post("/v1/generate", payload,
                                  payload.get("timeout_ms"))
            if self.gen_engine is None:
                raise ValueError(
                    f"replica {self.name!r} has no generation engine")
            from .generation import GenerationRequest
            greq = GenerationRequest(
                prompt=payload["prompt"],
                max_new_tokens=payload["max_new_tokens"],
                temperature=payload.get("temperature", 0.0),
                top_k=payload.get("top_k", 0),
                eos_id=payload.get("eos_id"),
                timeout_ms=payload.get("timeout_ms"),
                seed=payload.get("seed", 0))
            return self.gen_engine.submit(greq).result()

    def kv_export(self, prompt, run_prefill: bool = True) -> dict:
        """Disaggregated prefill: pack the prompt's full-block KV
        prefix into a kv_wire shipment (running chunked prefill through
        the replica's existing executable if not already resident)."""
        with self._track():
            if self.url is not None:
                return self._post(
                    "/v1/kv/export",
                    {"prompt": [int(t) for t in prompt],
                     "run_prefill": bool(run_prefill)}, None)
            if self.gen_engine is None:
                raise ValueError(
                    f"replica {self.name!r} has no generation engine")
            from . import disagg
            return disagg.export_prefix(self.gen_engine, prompt,
                                        run_prefill=run_prefill)

    def kv_adopt(self, payload: dict) -> dict:
        """Disaggregated decode: adopt a kv_wire shipment into the
        replica's local BlockPool/PrefixCache."""
        with self._track():
            if self.url is not None:
                return self._post("/v1/kv/adopt", payload, None)
            if self.gen_engine is None:
                raise ValueError(
                    f"replica {self.name!r} has no generation engine")
            from . import disagg
            return disagg.adopt_prefix(self.gen_engine, payload)

    def _post(self, path: str, payload: dict,
              timeout_ms: Optional[float]) -> dict:
        """POST to the replica server, translating its status codes
        back into the engine exception taxonomy so the router's
        failover logic is transport-agnostic. The current
        ``router.dispatch`` span's traceparent crosses the hop."""
        data = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        sp = trace.current_span()
        if sp is not None:
            headers["traceparent"] = trace.format_traceparent(sp)
        req = urllib.request.Request(self.url + path, data=data,
                                     headers=headers)
        timeout_s = (timeout_ms / 1e3 + 5.0) if timeout_ms else 30.0
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except Exception:
                body = {}
            msg = body.get("error", f"replica answered {e.code}")
            if e.code == 503:
                ra = e.headers.get("Retry-After") if e.headers else None
                if ra:
                    raise OverloadedError(msg,
                                          retry_after_s=float(ra))
                if body.get("retryable", True):
                    raise QueueFullError(msg)
                raise EngineClosedError(msg)
            if e.code == 504:
                raise DeadlineExceededError(msg)
            if e.code == 400:
                raise ValueError(msg)
            raise RuntimeError(f"replica {self.name!r}: {msg}")
        except urllib.error.URLError as e:
            raise ConnectionError(
                f"replica {self.name!r} unreachable: {e.reason}")

    # -- drain -----------------------------------------------------------

    def drain(self, timeout_s: float) -> bool:
        """Wait for in-flight (and in-process backend queues) to reach
        zero. True = fully drained before the deadline."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            if self.inflight() == 0 and (
                    self.url is not None or self.load() == 0):
                return True
            with self._cv:
                self._cv.wait(0.02)
        return self.inflight() == 0

    def _dec(self):
        with self._cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()


class _Inflight:
    def __init__(self, rep: Replica):
        self.rep = rep

    def __enter__(self):
        with self.rep._cv:
            self.rep._inflight += 1
        return self

    def __exit__(self, *exc):
        self.rep._dec()
        return False


class Router:
    """Health-gated least-loaded dispatcher over a set of `Replica`s.

    Thread-safe: dispatch, probe loop, hot-swap, and preempt/resume all
    take `_lock` only for table reads/writes — never across a backend
    call, so a slow replica can't wedge the router."""

    def __init__(self, replicas=(), probe_interval_s=None,
                 redispatch_budget=None, drain_timeout_s=None,
                 affinity_max=None, start_probe: bool = True,
                 disagg: Optional[bool] = None):
        from .disagg import FleetPrefixStore
        self.probe_interval_s = float(
            probe_interval_s if probe_interval_s is not None
            else FLAGS.router_probe_interval_s)
        self.disagg = bool(FLAGS.router_disagg if disagg is None
                           else disagg)
        # fleet-level content-addressed prefix registry (chain hash ->
        # owning replica names); maintained even with disagg off so a
        # flag flip needs no restart
        self.prefix_store = FleetPrefixStore()
        self.redispatch_budget = int(
            redispatch_budget if redispatch_budget is not None
            else FLAGS.router_redispatch_budget)
        self.drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else FLAGS.router_drain_timeout_s)
        self.affinity_max = int(
            affinity_max if affinity_max is not None
            else FLAGS.router_affinity_max)
        self._lock = threading.RLock()
        self._replicas: Dict[str, Replica] = {}
        # session -> replica-name pins, LRU-bounded at affinity_max so
        # a stream of short-lived sessions can't grow the map forever
        self._affinity: "OrderedDict[str, str]" = OrderedDict()
        # plain counters mirroring the serving.router_* stats, readable
        # without a monitor scrape (loadgen records them)
        self.requests = 0
        self.redispatches = 0
        self.shed = 0
        self._closed = False
        self._prev_sigterm = None
        self._sigterm_replicas: List[str] = []
        for r in replicas:
            self.add_replica(r)
        self._probe_stop = threading.Event()
        self._probe_thread = None
        if start_probe and self.probe_interval_s > 0:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="ptn-router-probe",
                daemon=True)
            self._probe_thread.start()

    # -- membership ------------------------------------------------------

    def add_replica(self, rep: Replica):
        with self._lock:
            if rep.name in self._replicas:
                raise ValueError(f"duplicate replica {rep.name!r}")
            rep.registered = True
            self._replicas[rep.name] = rep
        self._publish_gauges()
        flight_record("router_add_replica", replica=rep.name,
                      version=rep.version)

    def remove_replica(self, name: str, drain: bool = True,
                       stop: bool = False):
        with self._lock:
            rep = self._replicas.pop(name, None)
            self._drop_affinity_locked(name)
        if rep is None:
            return
        rep.registered = False
        # forget its fleet-store blocks: a chain entry pointing at a
        # gone replica would only buy failed transfers
        self.prefix_store.drop_owner(name)
        if drain:
            rep.drain(self.drain_timeout_s)
        if stop and rep.url is None:
            rep.stop()
        self._publish_gauges()

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def _drop_affinity_locked(self, name: str):
        for s, n in list(self._affinity.items()):
            if n == name:
                del self._affinity[s]

    # -- health ----------------------------------------------------------

    def _routable(self, rep: Replica, now: float) -> bool:
        # would_allow, not allow: this runs from read-only paths
        # (gauges, healthz, candidate filtering) and must never consume
        # a HALF_OPEN probe slot — _dispatch claims the slot via
        # allow() on the one replica it actually sends to
        return (rep.registered and rep.healthy
                and now >= rep.backoff_until
                and rep.breaker.would_allow())

    def healthy_replicas(self) -> List[Replica]:
        now = time.monotonic()
        with self._lock:
            reps = list(self._replicas.values())
        return [r for r in reps if self._routable(r, now)]

    def _probe_loop(self):
        while not self._probe_stop.wait(self.probe_interval_s):
            self.probe_once()

    def probe_once(self):
        """One active-probe sweep; callable directly from tests."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            try:
                h = rep.health()
                ok = h["state"] in _ROUTABLE_STATES
                ra = h.get("retry_after_s") or 0.0
            except Exception:
                ok, ra = False, 0.0
            if not ok:
                STAT_ADD("serving.router_probe_failures")
                if ra > 0:
                    rep.backoff_until = max(
                        rep.backoff_until, time.monotonic() + ra)
            if ok != rep.healthy:
                flight_record("router_health_flip", replica=rep.name,
                              healthy=ok)
            rep.healthy = ok
        self._publish_gauges()

    def _publish_gauges(self):
        now = time.monotonic()
        with self._lock:
            reps = list(self._replicas.values())
        STAT_SET("serving.router_replicas", len(reps))
        STAT_SET("serving.router_healthy_replicas",
                 sum(1 for r in reps if self._routable(r, now)))

    # -- dispatch --------------------------------------------------------

    # which replica roles may serve each dispatch kind: a prefill-only
    # worker must never absorb decode traffic (or skew least-loaded
    # picks), and vice versa; predict stays on unified replicas
    _KIND_ROLES = {"generate": ("unified", "decode"),
                   "prefill": ("unified", "prefill"),
                   "predict": ("unified",)}

    def _pick(self, kind: str, exclude, session: Optional[str],
              prefer: Optional[str] = None) -> Optional[Replica]:
        roles = self._KIND_ROLES.get(kind, ("unified",))
        now = time.monotonic()
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.name not in exclude
                    and r.role in roles
                    and self._routable(r, now)
                    and (r.url is not None
                         or (r.engine if kind == "predict"
                             else r.gen_engine) is not None)]
            if not reps:
                return None
            if prefer is not None:
                for r in reps:
                    if r.name == prefer:
                        if session is not None:
                            self._affinity[session] = r.name
                            self._affinity.move_to_end(session)
                        return r
            if session is not None:
                pinned = self._affinity.get(session)
                if pinned is not None:
                    self._affinity.move_to_end(session)
                for r in reps:
                    if r.name == pinned:
                        STAT_ADD("serving.router_affinity_hits")
                        return r
            best = min(reps, key=lambda r: (r.load(), r.name))
            if session is not None:
                self._affinity[session] = best.name
                self._affinity.move_to_end(session)
                while len(self._affinity) > self.affinity_max:
                    self._affinity.popitem(last=False)
            return best

    def _fleet_retry_after(self) -> float:
        """Max backoff across the fleet — the Retry-After an unhealthy
        router answers with. Pure read: bumps no counters, so healthz
        polls don't inflate the shed stat."""
        now = time.monotonic()
        with self._lock:
            reps = list(self._replicas.values())
        ra = 1.0
        for r in reps:
            ra = max(ra, r.breaker.retry_after_s(),
                     r.backoff_until - now)
        return ra

    def _shed_error(self) -> OverloadedError:
        STAT_ADD("serving.router_shed")
        with self._lock:
            self.shed += 1
        return OverloadedError(
            "no healthy replica (all replicas unhealthy, "
            "backing off, or deregistered)",
            retry_after_s=self._fleet_retry_after())

    def _dispatch(self, kind: str, call, session: Optional[str] = None,
                  prefer: Optional[str] = None):
        STAT_ADD("serving.router_requests")
        with self._lock:
            self.requests += 1
        t0 = time.perf_counter()
        tried = set()
        attempt = 0
        while True:
            # `prefer` only steers the FIRST pick (disagg phase 2:
            # decode must land where the KV was just adopted); failover
            # reverts to least-loaded
            rep = self._pick(kind, tried, session,
                             prefer=prefer if attempt == 0 else None)
            if rep is None:
                # every replica is out (or the budget exhausted the
                # healthy set): shed with Retry-After rather than
                # queueing work nobody can do
                raise self._shed_error()
            if not rep.breaker.allow():
                # raced: another thread claimed the last HALF_OPEN
                # probe slot between _pick's read-only check and here
                tried.add(rep.name)
                continue
            sp = trace.start_span(
                "router.dispatch",
                attrs={"replica": rep.name, "attempt": attempt,
                       "kind": kind})
            try:
                with trace.use_span(sp):
                    out = call(rep)
            except _RETRYABLE as e:
                trace.end_span(sp, error=type(e).__name__)
                rep.breaker.record_failure()
                ra = getattr(e, "retry_after_s", 0.0) or 0.0
                if ra > 0:
                    rep.backoff_until = max(
                        rep.backoff_until, time.monotonic() + ra)
                tried.add(rep.name)
                if session is not None:
                    with self._lock:
                        if self._affinity.get(session) == rep.name:
                            del self._affinity[session]
                attempt += 1
                if attempt > self.redispatch_budget:
                    raise
                STAT_ADD("serving.router_redispatches")
                with self._lock:
                    self.redispatches += 1
                flight_record("router_redispatch", replica=rep.name,
                              attempt=attempt,
                              error=type(e).__name__)
                continue
            except Exception:
                # non-retryable (bad request, deadline): the replica is
                # not at fault — don't punish its breaker, but hand
                # back the probe slot allow() may have claimed
                rep.breaker.release_probe()
                trace.end_span(sp, error="dispatch_error")
                raise
            trace.end_span(sp)
            rep.breaker.record_success()
            STAT_OBSERVE("serving.router_e2e_ms",
                         (time.perf_counter() - t0) * 1e3)
            return out

    def predict(self, feed: Dict[str, np.ndarray],
                timeout_ms: Optional[float] = None
                ) -> Dict[str, np.ndarray]:
        """Route one predict request; returns {output_name: array}."""
        return self._dispatch(
            "predict",
            lambda rep: rep.predict(feed, timeout_ms=timeout_ms))

    def generate(self, payload: dict,
                 session: Optional[str] = None) -> dict:
        """Route one generation request (a /v1/generate-shaped dict).
        `session` pins subsequent calls with the same key to the same
        replica while it stays healthy (KV prefix-cache affinity).
        With disagg on this becomes two-phase prefill->decode
        scheduling (see _generate_disagg)."""
        if self.disagg:
            return self._generate_disagg(payload, session)
        return self._dispatch(
            "generate", lambda rep: rep.generate(payload),
            session=session)

    # -- disaggregated prefill/decode dispatch --------------------------

    def _generate_disagg(self, payload: dict,
                         session: Optional[str] = None) -> dict:
        """Two-phase dispatch: pick the decode replica first (session
        affinity pins to it), consult the fleet prefix store, and only
        when the decode replica does not already own the prompt's
        full-block chain run the prefill hop (export on a
        prefill-capable peer, adopt on the decode replica). Any
        transfer failure — prefill worker death mid-transfer included
        — falls back to plain dispatch: the decode worker re-prefills
        locally, so answers never change, only latency."""
        from .kv_blocks import PrefixCache
        STAT_ADD("serving.disagg_requests")
        rep_d = self._pick("generate", set(), session)
        if rep_d is None:
            raise self._shed_error()
        prompt = [int(t) for t in payload.get("prompt", ())]
        store = self.prefix_store
        bs = store.block_size
        hashes: List[str] = []
        if bs and len(prompt) >= bs:
            hashes = PrefixCache.chunk_hashes(
                prompt[:(len(prompt) // bs) * bs], bs)
        need_xfer = bs is None or bool(
            hashes and store.owned_depth(hashes, rep_d.name)
            < len(hashes))
        if hashes and not need_xfer:
            STAT_ADD("serving.disagg_prefix_reuse")
        if need_xfer and (bs is None or hashes):
            try:
                self._disagg_transfer(prompt, rep_d, hashes, store)
            except Exception as e:
                STAT_ADD("serving.disagg_fallbacks")
                flight_record("disagg_fallback", replica=rep_d.name,
                              error=type(e).__name__)
        sp = trace.start_span("decode", attrs={"replica": rep_d.name})
        try:
            with trace.use_span(sp):
                out = self._dispatch(
                    "generate", lambda rep: rep.generate(payload),
                    session=session, prefer=rep_d.name)
        except Exception as e:
            trace.end_span(sp, error=type(e).__name__)
            raise
        trace.end_span(sp)
        return out

    def _disagg_transfer(self, prompt, rep_d: Replica,
                         hashes: List[str], store):
        """The prefill hop: export the prompt's KV prefix from a
        prefill-capable source and adopt it on the decode replica.
        Raises on any failure — the caller falls back."""
        from . import kv_wire
        src = None
        if hashes:
            owner = store.chain_owner(hashes, exclude=(rep_d.name,))
            if owner is not None:
                with self._lock:
                    cand = self._replicas.get(owner)
                if cand is not None and \
                        self._routable(cand, time.monotonic()):
                    src = cand
        if src is None:
            src = self._pick("prefill", {rep_d.name}, None)
        if src is None:
            raise OverloadedError(
                "no prefill-capable replica for KV transfer")
        t0 = time.perf_counter()
        sp = trace.start_span(
            "prefill", attrs={"replica": src.name,
                              "prompt_tokens": len(prompt)})
        try:
            with trace.use_span(sp):
                shipment = src.kv_export(prompt)
        except Exception as e:
            trace.end_span(sp, error=type(e).__name__)
            raise
        trace.end_span(sp)
        store.learn_block_size(int(shipment.get("block_size") or 0))
        hs = [str(h) for h in shipment.get("chain_hashes", ())]
        if not hs:
            return
        store.register(hs, src.name)
        nbytes = kv_wire.payload_bytes(shipment)
        sp = trace.start_span(
            "fetch", attrs={"src": src.name, "dst": rep_d.name,
                            "blocks": len(hs), "bytes": nbytes})
        try:
            with trace.use_span(sp):
                res = rep_d.kv_adopt(shipment)
        except Exception as e:
            trace.end_span(sp, error=type(e).__name__)
            raise
        trace.end_span(sp)
        resident = int(res.get("resident") or 0)
        if resident:
            store.register(hs[:resident], rep_d.name)
        STAT_ADD("serving.kv_xfer_blocks", len(hs))
        STAT_ADD("serving.kv_xfer_bytes", nbytes)
        STAT_OBSERVE("serving.kv_xfer_ms",
                     (time.perf_counter() - t0) * 1e3)

    # -- elasticity: hot swap -------------------------------------------

    def hot_swap(self, old_name: str, standby: Replica,
                 drain_timeout_s: Optional[float] = None) -> dict:
        """Zero-downtime model swap: warm `standby` through its full
        ladder while `old_name` keeps serving, gate on zero
        post-warmup compiles, atomically flip the table, drain the old
        replica, stop it. `standby.name == old_name` is allowed (the
        restart-with-new-weights pattern); any other name collision is
        rejected before the standby is ever started, and an abort on
        any later gate stops the standby so no warmed engine leaks.
        Call from any thread — traffic keeps flowing the whole time."""
        timeout = (drain_timeout_s if drain_timeout_s is not None
                   else self.drain_timeout_s)

        def _check_collision():
            # lock held; same-name swap is fine — old_name is popped
            # in the same critical section the standby goes in
            if standby.name != old_name and \
                    standby.name in self._replicas:
                raise ValueError(
                    f"duplicate replica {standby.name!r}")

        with self._lock:
            _check_collision()
        try:
            standby.start()
            compiles = standby.post_warmup_compiles()
            if compiles:
                raise RuntimeError(
                    f"hot-swap aborted: standby {standby.name!r} "
                    f"would compile in the serving path "
                    f"({compiles} post-warmup compiles)")
            with self._lock:
                _check_collision()   # re-check: add_replica may race
                old = self._replicas.pop(old_name, None)
                standby.registered = True
                self._replicas[standby.name] = standby
                self._drop_affinity_locked(old_name)
        except BaseException:
            try:
                standby.stop(drain=False)
            except Exception:
                pass
            raise
        self._publish_gauges()
        drained = True
        if old is not None:
            old.registered = False
            drained = old.drain(timeout)
            if old.url is None:
                old.stop(drain=True)
        STAT_ADD("serving.router_hot_swaps")
        flight_record("router_hot_swap", old=old_name,
                      new=standby.name, version=standby.version,
                      drained=drained)
        return {"swapped": True, "old": old_name,
                "new": standby.name, "version": standby.version,
                "drained": bool(drained),
                "standby_post_warmup_compiles": int(compiles)}

    # -- elasticity: preemption -----------------------------------------

    def preempt(self, name: str):
        """Deregister a replica (SIGTERM path): no new dispatches, but
        in-flight work finishes. The replica object stays known so
        `resume` can re-register it."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                return
            rep.registered = False
            self._drop_affinity_locked(name)
        STAT_ADD("serving.router_preemptions")
        flight_record("router_preempt", replica=name)
        self._publish_gauges()

    def resume(self, name: str):
        """Re-register a previously preempted replica."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                return
            rep.registered = True
            rep.healthy = True
            rep.backoff_until = 0.0
        flight_record("router_resume", replica=name)
        self._publish_gauges()

    def install_sigterm(self, *names: str):
        """Route SIGTERM through `preempt` for the named replicas,
        chaining any previously installed handler (same pattern as
        resilience/trainer_guard.py). No-op off the main thread —
        callers there use `preempt()` directly."""
        self._sigterm_replicas = list(names)
        if self._prev_sigterm is not None:
            return  # already installed; just updated the name list

        def _on_term(signum, frame):
            for n in self._sigterm_replicas:
                self.preempt(n)
            prev = self._prev_sigterm
            if callable(prev) and prev not in (signal.SIG_DFL,
                                               signal.SIG_IGN):
                prev(signum, frame)

        try:
            self._prev_sigterm = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            self._prev_sigterm = None

    # -- aggregate health ------------------------------------------------

    def healthz(self) -> tuple:
        """(http_code, body, retry_after_s) for the router's /healthz:
        200 while at least one replica is routable, else 503 with the
        fleet's max Retry-After."""
        now = time.monotonic()
        with self._lock:
            reps = list(self._replicas.values())
        detail = {r.name: {"registered": r.registered,
                           "healthy": r.healthy,
                           "version": r.version,
                           "role": r.role,
                           "load": r.load()} for r in reps}
        # informational only — a firing SLO alert never makes the
        # router stop routing (monitor_alerts.py)
        from .. import monitor_alerts
        firing = monitor_alerts.firing_count()
        if any(self._routable(r, now) for r in reps):
            return 200, {"state": "ok", "replicas": detail,
                         "alerts_firing": firing}, 0.0
        return 503, {"state": "open", "replicas": detail,
                     "alerts_firing": firing}, \
            self._fleet_retry_after()

    def close(self, stop_replicas: bool = False):
        self._closed = True
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        if stop_replicas:
            for rep in self.replicas():
                if rep.url is None:
                    rep.stop()


class RouterHTTP:
    """HTTP front end for a Router — same JSON protocol as the
    per-replica ServingHTTPServer (so clients can't tell a router from
    a replica), plus `X-Session-Id` / body ``"session"`` for
    generation affinity. Drains in-flight requests on close, like the
    replica server."""

    def __init__(self, router: Router, port: int = 0,
                 host: str = "127.0.0.1"):
        import http.server

        rt = router
        self.router = router
        # same lifecycle hook as ServingHTTPServer: a router front end
        # with FLAGS_alert_rules set runs the SLO evaluator
        from .. import monitor_alerts
        monitor_alerts.maybe_start()
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._draining = False
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            _span = None
            _last_code = None

            def _reply(self, code, payload, headers=None):
                self._last_code = code
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if self._span is not None:
                    self._span.set_attr("http.status", code)
                    self.send_header("X-Request-Id",
                                     self._span.trace_id)
                    self.send_header(
                        "traceparent",
                        trace.format_traceparent(self._span))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                STAT_ADD("serving.http_requests")
                if self.path.startswith("/healthz"):
                    code, body, ra = rt.healthz()
                    hdrs = None
                    if code != 200 and ra > 0:
                        hdrs = {"Retry-After":
                                str(max(1, int(round(ra))))}
                    self._reply(code, body, headers=hdrs)
                elif self.path.startswith("/metrics"):
                    from ..monitor import prometheus_text
                    body = prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length",
                                     str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.startswith("/alertz"):
                    from .. import monitor_alerts
                    self._reply(200, monitor_alerts.alertz_dict())
                else:
                    self._reply(404,
                                {"error": f"no route {self.path}"})

            def do_POST(self):
                STAT_ADD("serving.http_requests")
                with outer._inflight_cv:
                    if outer._draining:
                        draining = True
                    else:
                        draining = False
                        outer._inflight += 1
                if draining:
                    self._reply(503, {"error": "router is draining",
                                      "retryable": True})
                    self.close_connection = True
                    return
                try:
                    self._do_post()
                finally:
                    with outer._inflight_cv:
                        outer._inflight -= 1
                        if outer._inflight == 0:
                            outer._inflight_cv.notify_all()

            def _do_post(self):
                self._span = None
                self._last_code = None
                if trace.enabled():
                    remote = trace.parse_traceparent(
                        self.headers.get("traceparent"))
                    self._span = trace.start_span(
                        "http.request", remote=remote,
                        attrs={"method": "POST", "tier": "router",
                               "path": self.path.split("?")[0]})
                try:
                    with trace.use_span(self._span):
                        self._route_post()
                except BaseException as e:
                    trace.finish_trace(
                        self._span,
                        error=f"{type(e).__name__}: {e}")
                    self._span = None
                    raise
                else:
                    code = self._last_code
                    err = f"http {code}" \
                        if code is not None and code >= 400 else None
                    trace.finish_trace(self._span, error=err)
                    self._span = None

            def _route_post(self):
                try:
                    length = int(
                        self.headers.get("Content-Length", 0))
                    req = json.loads(
                        self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400,
                                {"error": f"bad request: {e}"})
                    return
                try:
                    if self.path.startswith("/v1/predict"):
                        inputs = req["inputs"]
                        if not isinstance(inputs, dict) or not inputs:
                            raise ValueError(
                                "'inputs' must be a non-empty object")
                        feed = {str(k): np.asarray(v)
                                for k, v in inputs.items()}
                        outs = rt.predict(
                            feed, timeout_ms=req.get("timeout_ms"))
                        self._reply(200, {
                            "outputs": {n: o.tolist()
                                        for n, o in outs.items()},
                            "shapes": {n: list(o.shape)
                                       for n, o in outs.items()}})
                    elif self.path.startswith("/v1/generate"):
                        session = req.pop("session", None) or \
                            self.headers.get("X-Session-Id")
                        if "prompt" not in req or \
                                "max_new_tokens" not in req:
                            raise ValueError(
                                "'prompt' and 'max_new_tokens' are "
                                "required")
                        out = rt.generate(req, session=session)
                        self._reply(200, out)
                    else:
                        self._reply(404, {"error":
                                          f"no route {self.path}"})
                except OverloadedError as e:
                    hdrs = None
                    s = getattr(e, "retry_after_s", 0.0) or 0.0
                    if s > 0:
                        hdrs = {"Retry-After":
                                str(max(1, int(round(s))))}
                    self._reply(503, {"error": str(e),
                                      "retryable": True},
                                headers=hdrs)
                except QueueFullError as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": True})
                except DeadlineExceededError as e:
                    self._reply(504, {"error": str(e)})
                except (EngineClosedError, ConnectionError) as e:
                    self._reply(503, {"error": str(e),
                                      "retryable": False})
                except (KeyError, TypeError, ValueError) as e:
                    self._reply(400,
                                {"error": f"bad request: {e}"})

            def log_message(self, *args):
                pass

        self._srv = http.server.ThreadingHTTPServer((host, port),
                                                    _Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            name="ptn-router-http", daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def close(self, drain: bool = True, timeout: float = 10.0):
        with self._inflight_cv:
            self._draining = True
        self._srv.shutdown()
        if drain:
            deadline = time.monotonic() + max(0.0, timeout)
            with self._inflight_cv:
                while self._inflight > 0:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._inflight_cv.wait(left)
        self._srv.server_close()

    stop = close

"""Paged KV-cache bookkeeping: block pool, block tables, prefix cache.

Reference: the reference framework's memory layer is built around a
pluggable block allocator (memory/allocation/allocator_facade.cc); this
module is its serving-side analogue, applied to KV-cache HBM the way
vLLM's PagedAttention applies OS paging to attention state. The device
side holds ONE physical pool per layer — `[num_blocks, block_size, h,
hd]` persistable tensors built by `models/gpt.build_paged_decode_step`
— and this module owns the host-side metadata:

* `BlockPool` — free-list allocator over the physical block ids with
  per-block refcounts. Physical block 0 is reserved as the SCRATCH
  block: muted decode rows route their (gated-off) writes there, so the
  fixed-shape graph never needs a conditional write path. A block with
  refcount > 1 is SHARED; sharing is copy-on-write in the degenerate
  form this design needs: only *full, immutable* prompt blocks are ever
  shared (the prefix cache below), so a write never targets a shared
  block and no device-side copy op is required. The refcount is what
  makes release safe: a finished slot decrefs its table and only
  unreferenced blocks return to the free list.

* `PrefixCache` — content-addressed map from a *chain hash* of prompt
  token blocks to the physical block already holding that KV. The hash
  of block j covers (hash of block j-1, tokens of block j), so a lookup
  can only match a prefix chain, never an interior block. Shared
  system-prompt traffic at millions-of-users scale hits here and skips
  re-prefill for the matched blocks entirely. The cache holds its own
  ref on every cached block; LRU eviction (oldest entry whose block
  nobody else references) runs when the pool is short.

Block metadata is deliberately layout-independent of the element type:
a block is identified by id and sized in tokens, so the planned int8 KV
leg (EQuARX-style quantization, arxiv 2506.17615) only changes
`block_bytes`, not the allocator, the tables, or the hash scheme.

Everything here is worker-thread-private (same ownership rule as
`SlotManager`), so there is no internal locking.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

__all__ = ["SCRATCH_BLOCK", "BlockPool", "PrefixCache",
           "blocks_for_tokens"]

# physical block 0: never allocated, never read — the write sink for
# muted rows in the fixed-shape paged graphs
SCRATCH_BLOCK = 0


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold `n_tokens` KV positions (ceil)."""
    if n_tokens <= 0:
        return 0
    return -(-int(n_tokens) // int(block_size))


class BlockPool:
    """Free-list + refcount allocator over `num_blocks` physical blocks.

    Ids run 1..num_blocks-1 (block 0 is `SCRATCH_BLOCK`). `alloc()`
    hands out the lowest free id first — deterministic, like
    `SlotManager` — with refcount 1; `incref`/`decref` manage sharing,
    and `decref` to zero returns the block to the free list.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"BlockPool: need >= 2 blocks (1 scratch + 1 usable), "
                f"got {num_blocks}")
        if block_size < 1:
            raise ValueError(
                f"BlockPool: block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # pop() returns the lowest id first
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = [0] * self.num_blocks

    def capacity(self) -> int:
        """Allocatable blocks (scratch excluded)."""
        return self.num_blocks - 1

    def free_count(self) -> int:
        return len(self._free)

    def used_count(self) -> int:
        return self.capacity() - len(self._free)

    def refcount(self, block_id: int) -> int:
        return self._ref[block_id]

    def alloc(self) -> Optional[int]:
        """Lowest free block id with refcount 1, or None when empty."""
        if not self._free:
            return None
        bid = self._free.pop()
        self._ref[bid] = 1
        return bid

    def incref(self, block_id: int):
        if block_id == SCRATCH_BLOCK or self._ref[block_id] < 1:
            raise ValueError(
                f"BlockPool: incref of unallocated block {block_id}")
        self._ref[block_id] += 1

    def decref(self, block_id: int):
        if block_id == SCRATCH_BLOCK or self._ref[block_id] < 1:
            raise ValueError(
                f"BlockPool: decref of unallocated block {block_id}")
        self._ref[block_id] -= 1
        if self._ref[block_id] == 0:
            self._free.append(block_id)
            self._free.sort(reverse=True)


class PrefixCache:
    """Chain-hash -> physical-block map for shared-prefix reuse.

    The cache owns one refcount on every entry's block, so cached KV
    survives the slot that produced it; `evict_lru()` releases the
    oldest entry whose block only the cache still references.
    """

    def __init__(self, pool: BlockPool):
        self.pool = pool
        # chain_hash -> block_id, in LRU order (move_to_end on touch)
        self._entries: "OrderedDict[str, int]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    @staticmethod
    def chunk_hashes(tokens: Sequence[int], block_size: int) -> List[str]:
        """One chain hash per FULL block of `tokens`: hash j covers
        (hash j-1, tokens of block j), so equal hashes imply equal
        whole prefixes. Partial tail blocks are not hashable — they are
        still mutable."""
        out: List[str] = []
        parent = b""
        n_full = len(tokens) // block_size
        for j in range(n_full):
            blk = tokens[j * block_size:(j + 1) * block_size]
            h = hashlib.sha1(
                parent + b"|" +
                b",".join(str(int(t)).encode() for t in blk)).hexdigest()
            out.append(h)
            parent = h.encode()
        return out

    def lookup(self, tokens: Sequence[int],
               max_tokens: Optional[int] = None) -> Tuple[int, List[int]]:
        """Longest cached prefix of `tokens` in full blocks.

        Returns (n_cached_tokens, block_ids); every returned block is
        incref'd FOR THE CALLER (a slot adopting them into its table
        releases them with `decref` like owned blocks). `max_tokens`
        caps the match (a prompt's last position must stay writable, so
        callers pass len(prompt) - 1).
        """
        bs = self.pool.block_size
        limit = len(tokens) if max_tokens is None else min(
            len(tokens), int(max_tokens))
        ids: List[int] = []
        for h in self.chunk_hashes(tokens[:limit], bs):
            bid = self._entries.get(h)
            if bid is None:
                break
            ids.append(bid)
            self._entries.move_to_end(h)
        for bid in ids:
            self.pool.incref(bid)
        return len(ids) * bs, ids

    def insert(self, chain_hash: str, block_id: int) -> bool:
        """Register a finished full prompt block. Returns False when the
        hash is already cached (first writer wins — the caller's block
        stays private to its slot)."""
        if chain_hash in self._entries:
            self._entries.move_to_end(chain_hash)
            return False
        self.pool.incref(block_id)
        self._entries[chain_hash] = block_id
        return True

    def evict_lru(self) -> Optional[int]:
        """Drop the oldest entry whose block only the cache holds
        (refcount == 1); returns the freed block id, or None when every
        cached block is still in use by a live slot."""
        for h, bid in self._entries.items():
            if self.pool.refcount(bid) == 1:
                del self._entries[h]
                self.pool.decref(bid)
                return bid
        return None

    def evictable_count(self) -> int:
        return sum(1 for bid in self._entries.values()
                   if self.pool.refcount(bid) == 1)

"""Run-level goodput accounting: an exclusive wall-clock ledger.

Per-op device timing (tools/op_profile.py) attributes *device* time but
says nothing about where the rest of a run's wall-clock went — and under
XLA fusion per-op numbers alone are misleading anyway.  This module adds
the missing layer above ops: every second between ``start_run()`` and
``end_run()`` is attributed to exactly one category:

  device_compute    dispatched step execution after warmup
  compile           first-run builds (trace + XLA compile) and warmup steps
  input_wait        consumer blocked on the reader (incl. injected stalls)
  feed_stage        host->device staging of feeds (device_put)
  fetch_sync        host blocking on fetch results (np.asarray sync)
  checkpoint_save   TrainerGuard durable checkpoint writes
  checkpoint_restore TrainerGuard resume/restore
  retry_backoff     RetryPolicy backoff sleeps
  nan_rollback      TrainerGuard in-memory rollback after a bad step
  preempt_drain     checkpoint-and-raise drain on a preemption signal
  probe_wait        bench.py backend probe wait (tunnel/TPU attach)
  other             residual (python glue, logging, snapshot copies)

``other`` is computed as the *residual* ``wall - sum(attributed)`` at
snapshot time, clamped at zero: under-attribution lands in ``other`` by
construction, while over-attribution (double counting) makes the category
sum exceed wall-clock — which is exactly what the sum≈wall invariant test
catches.  The goodput fraction is ``device_compute / wall``.

Everything is gated on ``FLAGS_enable_goodput`` via a cached flag handle
(the monitor.enabled() idiom): when off, every hook is one attribute read.
Stats are exported through the monitor registry, so ``FLAGS_enable_monitor``
additionally gates the ``goodput.*`` stat surface.

The input-starvation detector rides the reader hook: each batch wait is
observed into the ``goodput.input_wait_ms`` histogram and waits above
``FLAGS_goodput_starved_ms`` bump ``goodput.input_starved_steps``.
``start_run()`` appends a default ``input_starvation`` burn-rate rule to
``FLAGS_alert_rules`` (unless one is already configured), so firing and
incident bundling ride the existing monitor_alerts machinery unchanged.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .monitor import STAT_ADD, STAT_OBSERVE, STAT_SET

__all__ = [
    "CATEGORIES",
    "GoodputLedger",
    "start_run",
    "end_run",
    "active",
    "attribute",
    "note_input_wait",
    "snapshot",
    "export_snapshot",
    "check_invariant",
    "default_starvation_rule",
    "install_starvation_alert",
    "serving_busy",
    "serving_idle",
    "serving_pad_waste",
    "gen_busy",
    "gen_idle",
    "reset",
    "enabled",
]

CATEGORIES = (
    "device_compute",
    "compile",
    "input_wait",
    "feed_stage",
    "fetch_sync",
    "checkpoint_save",
    "checkpoint_restore",
    "retry_backoff",
    "nan_rollback",
    "preempt_drain",
    "probe_wait",
    "other",
)

# Literal stat names per category (the doc lint requires every documented
# stat name to exist as a string literal somewhere in the code corpus).
_CATEGORY_STATS = {
    "device_compute": "goodput.device_compute_seconds",
    "compile": "goodput.compile_seconds",
    "input_wait": "goodput.input_wait_seconds",
    "feed_stage": "goodput.feed_stage_seconds",
    "fetch_sync": "goodput.fetch_sync_seconds",
    "checkpoint_save": "goodput.checkpoint_save_seconds",
    "checkpoint_restore": "goodput.checkpoint_restore_seconds",
    "retry_backoff": "goodput.retry_backoff_seconds",
    "nan_rollback": "goodput.nan_rollback_seconds",
    "preempt_drain": "goodput.preempt_drain_seconds",
    "probe_wait": "goodput.probe_wait_seconds",
    "other": "goodput.other_seconds",
}

# Millisecond-oriented buckets for per-batch input wait: sub-ms queue pops
# up through multi-second stalls.
INPUT_WAIT_MS_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
)

# Cap on retained per-step waterfall records; the report only needs the
# worst-N, so a bounded deque keeps long runs O(1) in memory.
MAX_STEP_RECORDS = 4096

_flag = None


def enabled() -> bool:
    """Cheap cached check of FLAGS_enable_goodput (monitor.enabled idiom)."""
    global _flag
    f = _flag
    if f is None:
        from .core.flags import flag_handle

        f = _flag = flag_handle("enable_goodput")
    return f.value


def default_starvation_rule() -> str:
    """The default input-starvation burn-rate rule for FLAGS_alert_rules."""
    from .core.flags import FLAGS

    thresh = float(FLAGS.goodput_starved_ms)
    windows = FLAGS.goodput_alert_windows
    return ("input_starvation:burn:goodput.input_wait_ms:p50 > "
            "%g:windows=%s" % (thresh, windows))


def install_starvation_alert() -> str:
    """Append the default input_starvation rule to FLAGS_alert_rules.

    No-op when a rule named input_starvation is already configured, so
    operators can override the threshold/windows without fighting the
    default.  Returns the resulting rule string.
    """
    from .core.flags import FLAGS

    rules = FLAGS.alert_rules or ""
    if "input_starvation" in rules:
        return rules
    rule = default_starvation_rule()
    FLAGS.alert_rules = (rules + ";" + rule) if rules else rule
    return FLAGS.alert_rules


class GoodputLedger:
    """Thread-safe exclusive wall-clock ledger for one run."""

    def __init__(self, label: str = "run"):
        self.label = label
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._started_ts = time.time()
        self._end: Optional[float] = None
        self._cats: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self._steps: collections.deque = collections.deque(
            maxlen=MAX_STEP_RECORDS)
        self._pending_input_wait = 0.0
        self._n_steps = 0
        self._n_compile_steps = 0
        self._n_input_batches = 0
        self._n_starved = 0

    # -- attribution --------------------------------------------------

    def add(self, category: str, seconds: float) -> None:
        if seconds <= 0.0:
            return
        if category not in self._cats:
            category = "other"
        with self._lock:
            self._cats[category] += seconds

    def category_seconds(self, category: str) -> float:
        with self._lock:
            return self._cats.get(category, 0.0)

    def input_wait(self, seconds: float) -> None:
        """Reader hook: one consumer-side batch wait (incl. fault stalls).

        Accumulates into the input_wait category, folds into the *next*
        step's waterfall record (training loops pull a batch, then run),
        and drives the starvation detector.
        """
        from .core.flags import FLAGS

        if seconds < 0.0:
            seconds = 0.0
        wait_ms = seconds * 1000.0
        with self._lock:
            self._cats["input_wait"] += seconds
            self._pending_input_wait += seconds
            self._n_input_batches += 1
            starved = wait_ms > float(FLAGS.goodput_starved_ms)
            if starved:
                self._n_starved += 1
        STAT_OBSERVE("goodput.input_wait_ms", wait_ms,
                     buckets=INPUT_WAIT_MS_BUCKETS)
        STAT_ADD("goodput.input_batches")
        if starved:
            STAT_ADD("goodput.input_starved_steps")

    def note_step(self, *, feed_s: float, dispatch_s: float, fetch_s: float,
                  total_s: float, build_s: float = 0.0,
                  first_run: bool = False, backoff_s: float = 0.0) -> None:
        """Executor hook: attribute one run() call's sub-step timings.

        ``backoff_s`` is retry-backoff sleep that happened inside the
        dispatch span; RetryPolicy attributes it directly, so it is
        subtracted here to keep the categories exclusive.
        """
        compute_s = max(0.0, dispatch_s - backoff_s)
        compile_s = max(0.0, build_s)
        if first_run:
            # Warmup dispatch includes trace+XLA compile; count the whole
            # first execution as compile rather than productive compute.
            compile_s += compute_s
            compute_s = 0.0
        glue_s = max(0.0, total_s - feed_s - dispatch_s - fetch_s - build_s)
        with self._lock:
            pend = self._pending_input_wait
            self._pending_input_wait = 0.0
            self._cats["feed_stage"] += max(0.0, feed_s)
            self._cats["fetch_sync"] += max(0.0, fetch_s)
            self._cats["device_compute"] += compute_s
            self._cats["compile"] += compile_s
            self._cats["other"] += glue_s
            step = self._n_steps
            self._n_steps += 1
            if first_run:
                self._n_compile_steps += 1
            self._steps.append({
                "step": step,
                "input_wait_s": round(pend, 6),
                "feed_s": round(max(0.0, feed_s), 6),
                "compile_s": round(compile_s, 6),
                "compute_s": round(compute_s, 6),
                "fetch_s": round(max(0.0, fetch_s), 6),
                "other_s": round(glue_s, 6),
                "total_s": round(max(0.0, total_s) + pend, 6),
                "first_run": bool(first_run),
            })

    # -- reporting ----------------------------------------------------

    def end(self) -> None:
        with self._lock:
            if self._end is None:
                self._end = time.perf_counter()

    def wall_seconds(self) -> float:
        with self._lock:
            end = self._end if self._end is not None else time.perf_counter()
            return max(0.0, end - self._t0)

    def snapshot(self) -> Dict[str, Any]:
        """Exclusive category table + invariant check + waterfall records.

        ``other`` picks up the non-negative residual so the categories sum
        to wall-clock when attribution is consistent; double counting makes
        the sum exceed wall and shows up in ``sum_frac_err``.
        """
        wall = self.wall_seconds()
        with self._lock:
            cats = dict(self._cats)
            steps = list(self._steps)
            n_steps = self._n_steps
            n_compile = self._n_compile_steps
            n_batches = self._n_input_batches
            n_starved = self._n_starved
        attributed = sum(cats.values())
        cats["other"] += max(0.0, wall - attributed)
        total = sum(cats.values())
        frac = (cats["device_compute"] / wall) if wall > 0 else 0.0
        err = abs(total - wall) / wall if wall > 0 else 0.0
        snap = {
            "kind": "goodput_snapshot",
            "ts": time.time(),
            "label": self.label,
            "wall_s": round(wall, 6),
            "goodput_frac": round(frac, 6),
            "sum_frac_err": round(err, 6),
            "categories": {c: round(cats[c], 6) for c in CATEGORIES},
            "steps": n_steps,
            "compile_steps": n_compile,
            "post_warmup_compiles": max(0, n_compile - 1),
            "input_batches": n_batches,
            "starved_steps": n_starved,
            "step_records": steps,
        }
        self._publish(snap)
        return snap

    def _publish(self, snap: Dict[str, Any]) -> None:
        for cat, name in _CATEGORY_STATS.items():
            STAT_SET(name, snap["categories"][cat])
        STAT_SET("goodput.wall_seconds", snap["wall_s"])
        STAT_SET("goodput.fraction", snap["goodput_frac"])


# -- process-global active ledger -------------------------------------

_ACTIVE: Optional[GoodputLedger] = None
_ACTIVE_LOCK = threading.Lock()


def start_run(label: str = "run") -> Optional[GoodputLedger]:
    """Install a fresh ledger when FLAGS_enable_goodput is on.

    Also appends the default input_starvation alert rule to
    FLAGS_alert_rules so the detector has a firing path.  Returns None
    (and installs nothing) when goodput is disabled, so callers can
    invoke this unconditionally.
    """
    global _ACTIVE
    if not enabled():
        return None
    install_starvation_alert()
    led = GoodputLedger(label=label)
    with _ACTIVE_LOCK:
        _ACTIVE = led
    return led


def end_run() -> Optional[Dict[str, Any]]:
    """Freeze the active ledger's wall-clock and return its snapshot."""
    led = _ACTIVE
    if led is None:
        return None
    led.end()
    return led.snapshot()


def reset() -> None:
    """Drop the active ledger (tests)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def active() -> Optional[GoodputLedger]:
    """The active ledger, or None when goodput is off / no run started."""
    if not enabled():
        return None
    return _ACTIVE


def attribute(category: str, seconds: float) -> None:
    """Attribute seconds to a category on the active ledger (no-op off)."""
    led = _ACTIVE
    if led is None or not enabled():
        return
    led.add(category, seconds)


def note_input_wait(seconds: float) -> None:
    """Reader-side hook: one batch wait, with starvation detection."""
    led = _ACTIVE
    if led is None or not enabled():
        return
    led.input_wait(seconds)


def snapshot() -> Optional[Dict[str, Any]]:
    led = _ACTIVE
    if led is None:
        return None
    return led.snapshot()


def check_invariant(snap: Dict[str, Any], tol: float = 0.05) -> bool:
    """True when category seconds sum to wall-clock within tolerance."""
    wall = float(snap.get("wall_s") or 0.0)
    if wall <= 0.0:
        return False
    total = sum(float(v) for v in (snap.get("categories") or {}).values())
    return abs(total - wall) / wall <= tol


def export_snapshot(path: str, snap: Optional[Dict[str, Any]] = None) -> bool:
    """Append the (active) snapshot as one JSONL record to ``path``."""
    if snap is None:
        snap = snapshot()
    if snap is None:
        return False
    line = json.dumps(snap, sort_keys=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())
    return True


# -- serving-side busy/idle goodput ------------------------------------
#
# Serving loops have no step ledger: goodput there is busy vs idle time
# plus pad waste (the slack baked into ladder-padded batches).  These are
# monotonic float-second counters on the monitor registry.


def serving_busy(seconds: float) -> None:
    if not enabled() or seconds <= 0.0:
        return
    STAT_ADD("goodput.serving_busy_seconds", seconds)


def serving_idle(seconds: float) -> None:
    if not enabled() or seconds <= 0.0:
        return
    STAT_ADD("goodput.serving_idle_seconds", seconds)


def serving_pad_waste(seconds: float) -> None:
    if not enabled() or seconds <= 0.0:
        return
    STAT_ADD("goodput.serving_pad_waste_seconds", seconds)


def gen_busy(seconds: float) -> None:
    if not enabled() or seconds <= 0.0:
        return
    STAT_ADD("goodput.gen_busy_seconds", seconds)


def gen_idle(seconds: float) -> None:
    if not enabled() or seconds <= 0.0:
        return
    STAT_ADD("goodput.gen_idle_seconds", seconds)

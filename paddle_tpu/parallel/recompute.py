"""Activation recomputation (gradient checkpointing) over the Program IR.

Reference analogue: RecomputeOptimizer (optimizer.py:3313) +
`_append_backward_ops_with_checkpoints_` (backward.py:576): the forward is
split at user-marked checkpoint vars into segments; the backward re-runs
each segment's forward ops instead of keeping its activations live.

TPU-native formulation: each segment's ops move into a sub-block fronted by
one `recompute_segment` meta-op whose lowering evaluates the sub-block under
``jax.checkpoint``. The generic vjp grad (core/lowering.py) then recomputes
the segment in the backward automatically, and XLA's buffer assignment drops
the internal activations — the memory/FLOPs trade the reference implements
with hand-scheduled op copies falls out of one remat annotation.
"""
from __future__ import annotations

from typing import List

import jax

from ..core.registry import REGISTRY, register_op

__all__ = ["rewrite_program_for_recompute", "expose_fetch_vars"]


@register_op("recompute_segment")
def _recompute_segment(ctx, ins, attrs):
    names_in: List[str] = attrs["input_vars"]
    names_out: List[str] = attrs["output_vars"]
    block = ctx.sub_block(attrs["sub_block"])

    def seg(xs):
        env = dict(zip(names_in, xs))
        ctx.lower_sub_block(block, env)
        return [env[n] for n in names_out]

    outs = jax.checkpoint(seg)(list(ins["X"]))
    return {"Out": outs}


def _op_is_wrappable(op) -> bool:
    """Segments may only contain plain ops: inplace (optimizer) ops and ops
    with bespoke grad plumbing keep their own backward path."""
    if not REGISTRY.has(op.type):
        return False
    opdef = REGISTRY.get(op.type)
    return not opdef.inplace and opdef.custom_grad_maker is None \
        and op.type not in ("feed", "fetch", "recompute_segment")


def rewrite_program_for_recompute(program, checkpoints, keep_names=()):
    """Partition block-0's forward ops into segments ending at each
    checkpoint var; wrap every multi-op segment in a recompute_segment op.

    Must run BEFORE append_backward. ``keep_names`` (e.g. the loss) are
    always exposed as segment outputs.
    """
    block = program.global_block()
    checkpoints = {c.name if hasattr(c, "name") else str(c)
                   for c in checkpoints}
    keep = {k.name if hasattr(k, "name") else str(k) for k in keep_names}

    ops = list(block.ops)
    if not all(_op_is_wrappable(op) for op in ops):
        return  # control flow / custom-grad ops present: leave as-is

    # Split: a segment closes after the op that produces a checkpoint var.
    segments, cur = [], []
    for op in ops:
        cur.append(op)
        if any(n in checkpoints for n in op.output_names()):
            segments.append(cur)
            cur = []
    if cur:
        segments.append(cur)
    if len(segments) < 2:
        return

    persistable = {v.name for v in block.vars.values() if v.persistable}
    # consumers[name] = index of first segment reading it after production
    read_by_later: dict = {}
    for si, seg in enumerate(segments):
        for op in seg:
            for n in op.input_names():
                read_by_later.setdefault(n, set()).add(si)

    block.ops = []
    for si, seg in enumerate(segments):
        produced_here = set()
        consumed = []
        for op in seg:
            for n in op.input_names():
                if n and n not in produced_here and n not in consumed:
                    consumed.append(n)
            for n in op.output_names():
                if n:
                    produced_here.add(n)
        ext_in = [n for n in consumed if n not in produced_here]
        ext_out = sorted(
            n for n in produced_here
            if n in persistable or n in keep or n in checkpoints
            or any(sj > si for sj in read_by_later.get(n, ())))
        if len(seg) == 1:
            # single-op segment: nothing to recompute, keep it inline
            block.ops.append(seg[0])
            continue

        sub = program._create_block(parent_idx=block.idx)
        for op in seg:
            op.block = sub
            sub.ops.append(op)
        program._current_block_idx = block.idx

        block.append_op(
            "recompute_segment",
            inputs={"X": ext_in},
            outputs={"Out": ext_out},
            attrs={"sub_block": sub.idx,
                   "input_vars": ext_in,
                   "output_vars": ext_out},
            infer_shape=False)


def expose_fetch_vars(program, fetch_names):
    """Make fetch targets hidden inside recompute sub-blocks fetchable.

    A var produced inside a segment is normally an internal (recomputed)
    value; if the user fetches it, extend the owning recompute_segment op's
    outputs so it is materialised in the outer env. Called by
    Executor._compile; mutates the op attrs (the executable cache key
    already includes fetch_names, so each fetch set compiles consistently).
    """
    block = program.global_block()
    metas = [op for op in block.ops if op.type == "recompute_segment"]
    if not metas:
        return
    available = set()
    for op in block.ops:
        available.update(n for n in op.output_names() if n)
    for name in fetch_names:
        if name in available:
            continue
        for op in metas:
            sub = program.blocks[op.attrs["sub_block"]]
            if any(name in sop.output_names() for sop in sub.ops):
                new_out = list(op.attrs["output_vars"]) + [name]
                op.attrs = dict(op.attrs,
                                output_vars=new_out)
                op.outputs = dict(op.outputs, Out=new_out)
                program._fp_cache = None
                break

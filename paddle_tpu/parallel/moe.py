"""Expert parallelism: a switch-style MoE FFN sharded over the `ep`
mesh axis.

Absent from the 2019 reference (its scale story was PS sharding +
NCCL data parallelism); here expert parallelism is a first-class mesh
axis alongside dp/tp/pp/sp. Expert weights live sharded over `ep`
(each device holds E/ep experts); every device computes its local
experts' contribution for all tokens and a psum over `ep` combines
them — the dense-dispatch formulation, exact and static-shape. The
capacity-based sparse all-to-all dispatch is the optimization on top;
at equal expert count it changes cost, not numerics.

Gating is top-1 (Switch Transformer): the selected expert's output is
scaled by its softmax probability, so the router is trained through
the prob factor while the hard selection is a stop-gradient mask.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["moe_ffn", "moe_ffn_sharded", "moe_ffn_sparse",
           "moe_ffn_sparse_sharded", "init_moe_params"]


def init_moe_params(rng, n_experts, d_model, d_ff, dtype=jnp.float32):
    """{gate_w [d, E], w1 [E, d, f], b1 [E, f], w2 [E, f, d], b2 [E, d]}"""
    import numpy as np
    r = np.random.RandomState(rng)
    s1 = (2.0 / d_model) ** 0.5
    s2 = (2.0 / d_ff) ** 0.5
    return {
        "gate_w": jnp.asarray(
            r.randn(d_model, n_experts).astype(np.float32) * 0.02, dtype),
        "w1": jnp.asarray(
            r.randn(n_experts, d_model, d_ff).astype(np.float32) * s1,
            dtype),
        "b1": jnp.zeros((n_experts, d_ff), dtype),
        "w2": jnp.asarray(
            r.randn(n_experts, d_ff, d_model).astype(np.float32) * s2,
            dtype),
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def _route_top1(x, gate_w, e_global):
    """Top-1 switch routing, shared by every formulation: returns
    (probs [.., E], coef [.., E] = prob on the selected expert under a
    stop-grad mask, load = mean top-1 prob)."""
    logits = jnp.einsum("btd,de->bte", x, gate_w)
    probs = jax.nn.softmax(logits, axis=-1)
    mask = jax.nn.one_hot(jnp.argmax(probs, -1), e_global,
                          dtype=probs.dtype)
    coef = probs * jax.lax.stop_gradient(mask)
    return probs, coef, jnp.mean(jnp.max(probs, axis=-1))


def _expert_eval_all(x, params):
    """Every expert over every token: [B, E, T, d] outputs (the dense
    formulation's compute; also the exact single-device evaluation)."""
    h = jnp.einsum("btd,edf->betf", x, params["w1"]) \
        + params["b1"][None, :, None, :]
    h = jax.nn.gelu(h)
    return jnp.einsum("betf,efd->betd", h, params["w2"]) \
        + params["b2"][None, :, None, :]


def moe_ffn(x, params, axis_name="ep", n_experts_global=None,
            batch_axis=None):
    """Inside shard_map: x [B, T, d] (replicated or dp-sharded on B);
    params' expert arrays hold the LOCAL expert shard [E_local, ...];
    gate_w is replicated [d, E_global]. Returns y [B, T, d] (summed
    over the ep axis) and the router's mean top-1 prob (a load metric).
    """
    gate_w = params["gate_w"]
    w1, b1 = params["w1"], params["b1"]
    w2, b2 = params["w2"], params["b2"]
    e_local = w1.shape[0]
    e_global = n_experts_global or gate_w.shape[-1]
    idx = jax.lax.axis_index(axis_name)

    _, coef, local_load = _route_top1(x, gate_w, e_global)

    # local slice of the combine coefficients
    start = idx * e_local
    coef_local = jax.lax.dynamic_slice_in_dim(coef, start, e_local,
                                              axis=-1)  # [B, T, E_local]

    # every local expert computes all tokens; combine weighted
    out = _expert_eval_all(x, params)  # extra gate_w key is unused
    y = jnp.einsum("betd,bte->btd", out, coef_local)
    y = jax.lax.psum(y, axis_name)
    load = jax.lax.pmean(local_load, axis_name)
    if batch_axis is not None:
        # the metric is declared replicated (out_specs P()): reduce over
        # the batch axis too so every shard returns the GLOBAL mean
        load = jax.lax.pmean(load, batch_axis)
    return y, load


def _moe_shard_map(inner, x, params, mesh, ep_axis, batch_axis,
                   seq_axis=None, **kw):
    """Shared shard_map wrapper for the dense and sparse formulations:
    one place owns the spec layout (expert arrays sharded on dim 0 over
    ep, gate replicated, x optionally batch- and/or sequence-sharded).

    seq_axis composes MoE with sequence parallelism (dp x sp x ep):
    routing and expert compute are per-token, so sharding T changes
    which tokens each shard routes, not the math; only the load metric
    needs the extra pmean to stay global."""
    x_spec = P(batch_axis, seq_axis, None)
    param_specs = {"gate_w": P(None, None),
                   "w1": P(ep_axis, None, None), "b1": P(ep_axis, None),
                   "w2": P(ep_axis, None, None), "b2": P(ep_axis, None)}
    reduce_axes = tuple(a for a in (batch_axis, seq_axis) if a)
    fn = functools.partial(inner, axis_name=ep_axis,
                           n_experts_global=params["gate_w"].shape[-1],
                           batch_axis=reduce_axes or None, **kw)
    from ..core.jax_compat import shard_map
    sm = shard_map(fn, mesh=mesh, in_specs=(x_spec, param_specs),
                   out_specs=(x_spec, P()), check_vma=False)
    return sm(x, params)


def moe_ffn_sharded(x, params, mesh, ep_axis="ep", batch_axis=None,
                    seq_axis=None):
    """Global arrays -> shard_map over the mesh: expert arrays sharded
    on dim 0 over `ep_axis`, x replicated (or batch-sharded over
    `batch_axis` / sequence-sharded over `seq_axis`), output matching
    x."""
    return _moe_shard_map(moe_ffn, x, params, mesh, ep_axis, batch_axis,
                          seq_axis=seq_axis)


def moe_ffn_sparse(x, params, axis_name="ep", capacity=None,
                   n_experts_global=None, batch_axis=None):
    """Capacity-based sparse dispatch (the performance formulation):
    instead of every expert computing every token, tokens are packed
    into per-expert capacity buffers and exchanged with two all-to-alls
    over `ep`, so each expert computes only (up to) ep * capacity
    tokens. Tokens beyond an expert's capacity are DROPPED (output 0 +
    residual upstream), the standard Switch trade; capacity defaults to
    2x the even-load share. Numerics match moe_ffn exactly whenever no
    token is dropped (capacity >= tokens routed per expert).

    x [B, T, d] local; expert params local shards as in moe_ffn.
    Returns (y [B, T, d], load metric)."""
    gate_w = params["gate_w"]
    w1, b1 = params["w1"], params["b1"]
    w2, b2 = params["w2"], params["b2"]
    e_local = w1.shape[0]
    e_global = n_experts_global or gate_w.shape[-1]
    from ..core.jax_compat import axis_size
    n_shards = axis_size(axis_name)
    b, t, d = x.shape
    n = b * t
    if capacity is None:
        capacity = max(1, (2 * n + e_global - 1) // e_global)

    xt = x.reshape(n, d)
    logits = xt @ gate_w                                # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)                    # [N]
    coef = jnp.take_along_axis(probs, top[:, None], axis=-1)[:, 0]

    onehot = jax.nn.one_hot(top, e_global, dtype=jnp.int32)  # [N, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1       # [N, E]
    pos = jnp.max(pos, axis=-1)                         # [N] slot in expert
    keep = pos < capacity

    # dispatch buffers [E, C, d]: scatter kept tokens
    disp = jnp.zeros((e_global, capacity, d), x.dtype)
    safe_e = jnp.where(keep, top, 0)
    safe_p = jnp.where(keep, pos, 0)
    contrib = jnp.where(keep[:, None], xt, 0.0)
    disp = disp.at[safe_e, safe_p].add(contrib)

    # exchange: [ep, E_local, C, d] -> each shard holds its experts'
    # buffers from EVERY shard: [E_local, ep*C, d]
    disp = disp.reshape(n_shards, e_local, capacity, d)
    recv = jax.lax.all_to_all(disp, axis_name, split_axis=0,
                              concat_axis=2, tiled=True)
    recv = recv.reshape(e_local, n_shards * capacity, d)

    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", recv, w1)
                    + b1[:, None, :])
    out = jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]

    # exchange back: [E_local, ep, C, d] -> [E(=ep*E_local), C, d]
    out = out.reshape(e_local, n_shards, capacity, d)
    back = jax.lax.all_to_all(out, axis_name, split_axis=1,
                              concat_axis=0, tiled=True)
    back = back.reshape(e_global, capacity, d)

    y = back[safe_e, safe_p] * coef[:, None]
    y = jnp.where(keep[:, None], y, 0.0)
    load = jax.lax.pmean(jnp.mean(jnp.max(probs, axis=-1)), axis_name)
    if batch_axis is not None:
        load = jax.lax.pmean(load, batch_axis)
    return y.reshape(b, t, d), load


def moe_ffn_sparse_sharded(x, params, mesh, ep_axis="ep", capacity=None,
                           batch_axis=None, seq_axis=None):
    """Global-array wrapper for moe_ffn_sparse (same specs as
    moe_ffn_sharded)."""
    return _moe_shard_map(moe_ffn_sparse, x, params, mesh, ep_axis,
                          batch_axis, seq_axis=seq_axis, capacity=capacity)


# ---------------------------------------------------------------------------
# Program-IR op + fluid.layers front-end
# ---------------------------------------------------------------------------

def _moe_ffn_op(ctx, ins, attrs):
    """Program-IR face: inputs X [B,T,d], GateW [d,E], W1 [E,d,f],
    B1 [E,f], W2 [E,f,d], B2 [E,d]. With a mesh carrying the `ep` axis
    the sharded (dense or capacity-sparse) formulation runs; otherwise
    a single-device dense evaluation with identical routing math."""
    x = ins["X"][0]
    params = {"gate_w": ins["GateW"][0], "w1": ins["W1"][0],
              "b1": ins["B1"][0], "w2": ins["W2"][0], "b2": ins["B2"][0]}
    ep_axis = attrs.get("ep_axis", "ep")
    if ctx.mesh is not None and ep_axis in ctx.mesh.axis_names:
        batch_axis = attrs.get("batch_axis", "dp")
        if batch_axis not in ctx.mesh.axis_names:
            batch_axis = None
        if attrs.get("capacity"):
            y, load = moe_ffn_sparse_sharded(
                x, params, ctx.mesh, ep_axis=ep_axis,
                capacity=attrs["capacity"], batch_axis=batch_axis)
        else:
            y, load = moe_ffn_sharded(x, params, ctx.mesh,
                                      ep_axis=ep_axis,
                                      batch_axis=batch_axis)
        return {"Out": [y], "Load": [load]}
    # single-device exact evaluation: the SAME routing/expert helpers
    # the sharded formulations use
    e = params["gate_w"].shape[-1]
    _, coef, load = _route_top1(x, params["gate_w"], e)
    out = _expert_eval_all(x, params)
    y = jnp.einsum("betd,bte->btd", out, coef)
    return {"Out": [y], "Load": [load]}


def _register():
    from ..core.registry import register_op
    register_op("moe_ffn", nondiff_outputs=("Load",))(_moe_ffn_op)


_register()

"""Parallelism: mesh/sharding utilities, collectives, SPMD training.

Reference scope: SURVEY.md §2.7 — ParallelExecutor DP, collective
transpiler, hierarchical allreduce, pipeline, recompute... re-expressed as
jax.sharding meshes + GSPMD + shard_map collectives over ICI/DCN.
"""
from .api import ParallelExecutor  # noqa: F401
from .mesh import get_mesh, set_mesh, mesh_context  # noqa: F401
from .layout import SpecLayout, mesh_from_spec  # noqa: F401
from . import ring_attention  # noqa: F401  (registers the op)
from . import recompute  # noqa: F401  (registers recompute_segment)
from .pipeline import gpipe, stack_stage_params, SectionPipeline  # noqa: F401
from .moe import (moe_ffn, moe_ffn_sharded, moe_ffn_sparse,  # noqa: F401
                  moe_ffn_sparse_sharded, init_moe_params)
from .ulysses import ulysses_attention, ulysses_attention_sharded  # noqa: F401

"""TPU-native pipeline parallelism (GPipe schedule over a mesh axis).

Reference analogue: PipelineOptimizer (optimizer.py:3020) cuts a Program
into sections streamed through ScopeQueues by PipelineTrainer/SectionWorker
threads (trainer.h:115-160) — a host-scheduled, queue-based pipeline.

On TPU the idiomatic equivalent is an SPMD collective-permute pipeline
(scaling-book recipe): every pipeline stage lives on its own slice of a
``pp`` mesh axis, holds its own stage parameters, and activations flow
stage→stage over ICI via ``lax.ppermute`` inside a ``lax.scan`` over the
microbatch clock. Fill/drain bubbles, microbatch scheduling and the reverse
(backward) schedule all fall out of the scan + ppermute structure: jax.grad
differentiates through it, and the transpose of ppermute is the reverse
permute, so the backward pass is automatically the mirrored pipeline.

Homogeneous stages (e.g. N identical transformer layers) are required —
the same constraint the stacked-parameter SPMD formulation always has; the
reference's heterogeneous CPU↔GPU sections map instead to ``SectionPipeline``
below (sequential microbatching with gradient accumulation, the semantic
fallback).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.jax_compat import pcast, shard_map

from .mesh import get_mesh

__all__ = ["gpipe", "stack_stage_params", "SectionPipeline"]


def stack_stage_params(params_list):
    """Stack per-stage parameter pytrees along a new leading stage axis.

    [{'w': [d,d]}, ...] * n_stages -> {'w': [n_stages, d, d]} — the layout
    gpipe expects (stage axis sharded over the ``pp`` mesh axis).
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def gpipe(stage_fn: Callable, stacked_params, x, *, n_microbatches: int,
          mesh=None, axis: str = "pp"):
    """Run ``n_stages`` copies of ``stage_fn`` as a pipeline over ``axis``.

    stage_fn(stage_params, acts) -> acts   (activation shape preserved)
    stacked_params: pytree with leading dim n_stages (see stack_stage_params)
    x: [batch, ...] global input; batch must divide by n_microbatches.

    Differentiable end-to-end: wrap in jax.grad for pipelined training.
    """
    mesh = mesh or get_mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} % n_microbatches {n_microbatches}")
    x_mb = x.reshape(n_microbatches, batch // n_microbatches, *x.shape[1:])

    def run(params, x_mb):
        local = jax.tree.map(lambda a: a[0], params)  # this stage's slice
        idx = jax.lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(n_stages - 1)]
        n_micro = x_mb.shape[0]

        def body(carry, t):
            state, outputs = carry
            # stage 0 injects microbatch t; later stages consume the
            # activation ppermuted from stage-1 on the previous tick
            inp = jnp.where(idx == 0, x_mb[jnp.clip(t, 0, n_micro - 1)],
                            state)
            y = stage_fn(local, inp)
            # last stage finishes microbatch m = t - (n_stages-1)
            m = t - (n_stages - 1)
            slot = jnp.clip(m, 0, n_micro - 1)
            keep = (idx == n_stages - 1) & (m >= 0)
            prev = jax.lax.dynamic_index_in_dim(outputs, slot, 0,
                                                keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(keep, y, prev), slot, 0)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, outputs), None

        # The carry is device-varying over the pp axis (each stage holds a
        # different activation), so the init must be cast to varying for
        # shard_map's per-axis type check to accept the scan.
        init = pcast((jnp.zeros_like(x_mb[0]),
                      jnp.zeros_like(x_mb)), axis, to="varying")
        (_, outputs), _ = jax.lax.scan(
            body, init, jnp.arange(n_microbatches + n_stages - 1))
        # outputs are only valid on the last stage; replicate across pp
        mask = (idx == n_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    pspec = jax.tree.map(lambda _: P(axis), stacked_params)
    out = shard_map(run, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                    axis_names={axis})(stacked_params, x_mb)
    return out.reshape(batch, *out.shape[2:])


class SectionPipeline:
    """Heterogeneous-section fallback: reference PipelineOptimizer semantics
    (sections run in order per microbatch, gradients accumulated across
    microbatches). On one chip this is microbatched gradient accumulation —
    XLA overlaps section compute; there is no host queue to schedule.
    """

    def __init__(self, section_fns, n_microbatches: int):
        self.sections = list(section_fns)
        self.n_microbatches = n_microbatches

    def _check_batch(self, x):
        if x.shape[0] % self.n_microbatches:
            raise ValueError(f"batch {x.shape[0]} % n_microbatches "
                             f"{self.n_microbatches}")

    def forward(self, params_per_section, x):
        self._check_batch(x)
        mbs = jnp.split(x, self.n_microbatches)
        outs = []
        for mb in mbs:
            h = mb
            for fn, p in zip(self.sections, params_per_section):
                h = fn(p, h)
            outs.append(h)
        return jnp.concatenate(outs)

    def grad(self, loss_fn, params_per_section, x, y):
        """Mean loss + grads accumulated over microbatches (one XLA
        program; scan keeps the HLO small for many microbatches)."""
        self._check_batch(x)
        xm = jnp.stack(jnp.split(x, self.n_microbatches))
        ym = jnp.stack(jnp.split(y, self.n_microbatches))

        def one(carry, xy):
            xb, yb = xy

            def f(ps):
                h = xb
                for fn, p in zip(self.sections, ps):
                    h = fn(p, h)
                return loss_fn(h, yb)

            l, g = jax.value_and_grad(f)(params_per_section)
            loss_acc, grad_acc = carry
            return (loss_acc + l,
                    jax.tree.map(jnp.add, grad_acc, g)), None

        zero = (jnp.zeros(()),
                jax.tree.map(jnp.zeros_like, params_per_section))
        (loss, grads), _ = jax.lax.scan(one, zero, (xm, ym))
        k = self.n_microbatches
        return loss / k, jax.tree.map(lambda g: g / k, grads)

"""ParallelExecutor: source-compatible facade over the GSPMD path.

Reference: fluid.ParallelExecutor (parallel_executor.cc:393) — local scopes
per device, NCCL bcast of params, SSA-graph executor selection. On TPU all
of that collapses to CompiledProgram.with_data_parallel + Executor.run; this
class keeps the constructor/run signature for ported scripts.
"""
from __future__ import annotations

from ..compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from ..executor import Executor
from ..framework import default_main_program

__all__ = ["ParallelExecutor"]


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, mesh=None, layout=None):
        program = main_program or default_main_program()
        self._compiled = CompiledProgram(
            program, build_strategy or BuildStrategy()).with_data_parallel(
                loss_name=loss_name,
                exec_strategy=exec_strategy or ExecutionStrategy())
        # Explicit sharded path (the FLAGS_sharded_exec executor gate
        # attaches the same thing automatically for plain instances):
        # a mesh plus an optional SpecLayout for ZeRO/tensor sharding.
        if mesh is not None:
            if layout is None:
                from .layout import SpecLayout
                layout = SpecLayout(mesh).add_program(program)
            axes = (layout.data_axis,) if getattr(
                layout, "data_axis", None) else ("dp",)
            self._compiled.with_distributed(mesh, state_spec_fn=layout,
                                            batch_axes=axes)
        self._executor = Executor()
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._executor.run(self._compiled, feed=feed,
                                  fetch_list=fetch_list, scope=self._scope,
                                  return_numpy=return_numpy)

"""Global device-mesh registry.

The reference keys NCCL communicators by ring_id (collective_helper.h
NCCLCommContext). Here the analogue is a named-axis Mesh; collective ops
carry a ring_id attr that maps to a mesh axis name via this registry.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

_current_mesh: Optional[Mesh] = None


def make_mesh(shape=None, axis_names=None, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (devices.size,)
        axis_names = axis_names or ("dp",)
    return Mesh(devices.reshape(shape), axis_names=tuple(axis_names))


def set_mesh(mesh: Mesh):
    global _current_mesh
    _current_mesh = mesh


def get_mesh() -> Mesh:
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = make_mesh()
    return _current_mesh


@contextlib.contextmanager
def mesh_context(mesh: Mesh):
    global _current_mesh
    old = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = old


def axis_for_ring(ring_id: int) -> str:
    """Map a reference-style ring_id to a mesh axis name: ring 0 = first
    axis (the data-parallel ring in the collective transpiler)."""
    mesh = get_mesh()
    names = list(mesh.axis_names)
    return names[min(ring_id, len(names) - 1)]

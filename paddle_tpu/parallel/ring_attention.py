"""Ring attention: sequence/context parallelism for long sequences.

Absent from the 2019 reference (SURVEY.md §2.7 'not present') — its sequence
story was LoD ragged tensors. Here long context is first-class: Q/K/V are
sharded over the sequence axis of the mesh; each device holds one sequence
chunk and K/V blocks rotate around the ring via lax.ppermute (XLA
CollectivePermute over ICI), overlapping transfer with the block-attention
compute. Softmax is combined across blocks with the online log-sum-exp
merge, so the result is bit-comparable to full attention.

Layers on jax shard_map; usable three ways:
- `ring_attention(q, k, v, axis_name=...)` inside an existing shard_map;
- `ring_attention_sharded(q, k, v, mesh, axis)` — wraps itself in
  shard_map over global arrays (what the `ring_attention` op lowering
  uses, nestable under the Executor's jit);
- the `ring_attention` op in a Program (ops registered below).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..core.jax_compat import axis_size
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _masked_scores(q, k_blk, sm_scale, q_off, k_off, causal):
    """Scaled qk^T scores with the causal mask applied — shared by the
    forward block attention and the blockwise ring backward so the two
    can never desynchronize."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        tq, tk = q.shape[2], k_blk.shape[2]
        qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        kpos = k_off + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where((qpos >= kpos)[None, None], s, NEG_INF)
    return s


def _block_attn(q, k, v, sm_scale, q_off, k_off, causal, live=None):
    """Attention of local q against one k/v block, returning (o, lse).
    q: [b, h, tq, d]; k/v: [b, h, tk, d]. `live` (optional [tk] bool)
    masks padded keys out of the block softmax."""
    s = _masked_scores(q, k, sm_scale, q_off, k_off, causal)
    if live is not None:
        s = jnp.where(live[None, None, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # avoid -inf - -inf
    p = jnp.exp(s - m)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-20)
    o = jnp.einsum("bhqk,bhkd->bhqd", (p / l).astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    lse = m + jnp.log(l)
    return o, lse  # o normalised within the block; merge by lse weights


def _lse_merge(o, lse, o_i, lse_i):
    """Online softmax merge over the union of seen keys — the single
    home for this math (used by the ring forward and the ulysses
    blockwise path; the ring backward recomputes from saved lse)."""
    new_lse = jnp.logaddexp(lse, lse_i)
    o = (o * jnp.exp(lse - new_lse).astype(o.dtype)
         + o_i * jnp.exp(lse_i - new_lse).astype(o.dtype))
    return o, new_lse


def _ring_fwd_loop(q, k, v, axis_name, causal, sm_scale):
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[2]
    q_off = idx * t_local
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        o, lse, kv = carry
        k_blk, v_blk = kv
        src = (idx - i) % n  # whose chunk we hold at step i
        k_off = src * t_local
        o_i, lse_i = _block_attn(q, k_blk, v_blk, sm_scale, q_off, k_off,
                                 causal)
        o, new_lse = _lse_merge(o, lse, o_i, lse_i)
        kv = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)
        return o, new_lse, kv

    b, h, t, d = q.shape
    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    lse0 = jnp.full((b, h, t, 1), NEG_INF, jnp.float32)
    o, lse, _ = jax.lax.fori_loop(0, n, step, (o0, lse0, (k, v)))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring(q, k, v, axis_name, causal, sm_scale):
    o, _ = _ring_fwd_loop(q, k, v, axis_name, causal, sm_scale)
    return o


def _ring_vjp_fwd(q, k, v, axis_name, causal, sm_scale):
    o, lse = _ring_fwd_loop(q, k, v, axis_name, causal, sm_scale)
    # after n rotations k/v are home again: residuals are the originals
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(axis_name, causal, sm_scale, res, do):
    """FlashAttention-2-style blockwise backward around the ring: each
    step recomputes p = exp(s - lse) for the currently-held k/v chunk,
    accumulates dq locally, and accumulates dk/dv into buffers that
    ROTATE WITH the chunk — after the full ring the buffers land back on
    the chunk's owner. All dots take bf16 operands with f32 accumulation
    (a custom-vjp backward is safe from jax's dot-transpose f32
    poisoning; see ops/math.py:_mul)."""
    q, k, v, o, lse = res
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[2]
    q_off = idx * t_local
    perm = [(j, (j + 1) % n) for j in range(n)]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [b, h, tq, 1]

    def step(i, carry):
        dq, kv, dkv = carry
        k_blk, v_blk = kv
        dk_acc, dv_acc = dkv
        src = (idx - i) % n
        k_off = src * t_local
        s = _masked_scores(q, k_blk, sm_scale, q_off, k_off, causal)
        p = jnp.exp(s - lse)                       # [b, h, tq, tk] f32
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, v_blk,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        ds_l = ds.astype(q.dtype)
        p_l = p.astype(q.dtype)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds_l, k_blk,
                             preferred_element_type=jnp.float32)
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds_l, q,
                                     preferred_element_type=jnp.float32)
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", p_l, do,
                                     preferred_element_type=jnp.float32)
        kv, dkv = jax.lax.ppermute(
            ((k_blk, v_blk), (dk_acc, dv_acc)), axis_name, perm)
        return dq, kv, dkv

    b, h, t, d = q.shape
    zeros = jnp.zeros((b, h, t, d), jnp.float32)
    dq, _, (dk, dv) = jax.lax.fori_loop(
        0, n, step, (zeros, (k, v), (zeros, zeros)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, axis_name, causal=False, sm_scale=None):
    """Inside shard_map: q,k,v are the LOCAL sequence chunks
    [b, h, t_local, d]. Returns local attention output chunk.
    Differentiable via a blockwise ring backward (custom vjp)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if not isinstance(sm_scale, (int, float)):
        # custom_vjp nondiff args must be static; fail with the contract
        # spelled out instead of a ConcretizationTypeError deep inside
        raise TypeError(
            "ring_attention: sm_scale must be a static python number "
            f"(got {type(sm_scale).__name__}); close over the value "
            "instead of passing it as a traced array")
    return _ring(q, k, v, axis_name, causal, float(sm_scale))


def ring_attention_sharded(q, k, v, mesh, seq_axis, causal=False,
                           sm_scale=None, batch_axis=None):
    """Global [b, h, T, d] arrays -> shard_map over the mesh seq axis
    (+ optional batch axis on dim 0)."""
    from jax.experimental.shard_map import shard_map
    spec = P(batch_axis, None, seq_axis, None)

    fn = functools.partial(ring_attention, axis_name=seq_axis,
                           causal=causal, sm_scale=sm_scale)
    sm = shard_map(lambda q_, k_, v_: fn(q_, k_, v_), mesh=mesh,
                   in_specs=(spec, spec, spec), out_specs=spec,
                   check_rep=False)
    return sm(q, k, v)


# ---------------------------------------------------------------------------
# Program-IR op
# ---------------------------------------------------------------------------

def seq_parallel_attention_op(sharded_fn):
    """Shared Program-IR op body for the sequence-parallel attention
    schemes (ring / Ulysses): attrs parsing, single-device flash
    fallback (also used when the mesh lacks the seq axis — the inputs
    are then unsharded on it, so exact attention is the same math),
    and graceful batch-axis degradation."""

    def _op(ctx, ins, attrs):
        q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
        seq_axis = attrs.get("seq_axis", "sp")
        if ctx.mesh is None or seq_axis not in ctx.mesh.axis_names:
            from ..ops.pallas.flash_attention import flash_attention
            return {"Out": [flash_attention(
                q, k, v, causal=attrs.get("causal", False),
                sm_scale=attrs.get("sm_scale"))]}
        batch_axis = attrs.get("batch_axis", "dp")
        if batch_axis not in ctx.mesh.axis_names:
            batch_axis = None
        out = sharded_fn(
            q, k, v, ctx.mesh, seq_axis,
            causal=attrs.get("causal", False),
            sm_scale=attrs.get("sm_scale"), batch_axis=batch_axis)
        return {"Out": [out]}
    return _op


_ring_attention_op = seq_parallel_attention_op(ring_attention_sharded)


def _register():
    from ..core.registry import register_op
    register_op("ring_attention")(_ring_attention_op)


_register()

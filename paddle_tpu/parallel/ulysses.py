"""Ulysses-style all-to-all sequence parallelism.

The second long-context scheme next to [[ring attention]]
(parallel/ring_attention.py): instead of rotating K/V blocks around a
ring, TWO all-to-alls re-partition the work — the first trades the
sequence sharding for a HEAD sharding (each device receives the full
sequence for h/sp of the heads), exact local attention runs per head
group, and the second all-to-all restores the sequence sharding.

Communication is 2 x all-to-all of the activations (O(b·t·d/sp) per
device over ICI) vs the ring's (sp-1) k/v ppermutes; attention math is
exact in both. Requires sp | n_heads.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention(q, k, v, axis_name="sp", causal=False,
                      sm_scale=None):
    """Inside shard_map: q/k/v are LOCAL sequence chunks
    [b, h, t_local, d] with h divisible by the axis size. Returns the
    local output chunk [b, h, t_local, d]."""
    from ..core.jax_compat import axis_size
    n = axis_size(axis_name)
    h = q.shape[1]
    if h % n:
        raise ValueError(
            f"ulysses_attention: heads ({h}) must divide by the "
            f"sequence-parallel degree ({n}); use ring attention for "
            f"head counts below the mesh axis size")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    def scatter_heads(x):
        # [b, h, t/n, d] -> [b, h/n, t, d]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def gather_heads(x):
        # [b, h/n, t, d] -> [b, h, t/n, d]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qf, kf, vf = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # exact attention over the full sequence for the local head group,
    # computed blockwise over K/V (online log-sum-exp merge) so per-
    # device memory is O(T·block), not the O(T^2) score matrix — dense
    # softmax would OOM at exactly the long-context lengths sequence
    # parallelism targets. Math shared with the ring scheme (positions
    # are global after the scatter, so offsets are 0).
    o = _blockwise_full_attn(qf, kf, vf, sm_scale, causal)
    return gather_heads(o)


def _blockwise_full_attn(q, k, v, sm_scale, causal, block_k=512):
    """Exact attention of q against the FULL k/v, scanning k/v in
    blocks with the same online-lse merge as the ring forward
    (ring_attention._block_attn). q/k/v: [b, h, T, d]."""
    from .ring_attention import NEG_INF, _block_attn

    t = k.shape[2]
    if t <= block_k:
        o, _ = _block_attn(q, k, v, sm_scale, 0, 0, causal)
        return o.astype(q.dtype)
    nb = -(-t // block_k)
    pad = nb * block_k - t
    if pad:
        # padded keys are masked out of the merge via -inf scores
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    else:
        kp, vp = k, v

    from .ring_attention import _lse_merge

    def step(i, carry):
        o, lse = carry
        k_blk = jax.lax.dynamic_slice_in_dim(kp, i * block_k, block_k, 2)
        v_blk = jax.lax.dynamic_slice_in_dim(vp, i * block_k, block_k, 2)
        # mask padded keys out of the final block's softmax
        live = (i * block_k + jnp.arange(block_k) < t) if pad else None
        o_i, lse_i = _block_attn(q, k_blk, v_blk, sm_scale, 0,
                                 i * block_k, causal, live=live)
        return _lse_merge(o, lse, o_i, lse_i)

    b, h, tq, d = q.shape
    o0 = jnp.zeros((b, h, tq, d), jnp.float32)
    lse0 = jnp.full((b, h, tq, 1), NEG_INF, jnp.float32)
    o, _ = jax.lax.fori_loop(0, nb, step, (o0, lse0))
    return o.astype(q.dtype)


def ulysses_attention_sharded(q, k, v, mesh, seq_axis, causal=False,
                              sm_scale=None, batch_axis=None):
    """Global [b, h, T, d] arrays -> shard_map over the mesh seq axis
    (same contract as ring_attention_sharded)."""
    spec = P(batch_axis, None, seq_axis, None)
    fn = functools.partial(ulysses_attention, axis_name=seq_axis,
                           causal=causal, sm_scale=sm_scale)
    # core.jax_compat: jax.shard_map (check_vma) on new jax, the
    # experimental home (check_rep) on old
    from ..core.jax_compat import shard_map
    sm = shard_map(lambda q_, k_, v_: fn(q_, k_, v_), mesh=mesh,
                   in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=False)
    return sm(q, k, v)


# ---------------------------------------------------------------------------
# Program-IR op (same contract as the ring_attention op)
# ---------------------------------------------------------------------------

def _register():
    from ..core.registry import register_op
    from .ring_attention import seq_parallel_attention_op
    register_op("ulysses_attention")(
        seq_parallel_attention_op(ulysses_attention_sharded))


_register()

"""SpecLayout: program-var -> PartitionSpec table over a Mesh(data, model).

Reference analogue: the distributed transpiler's per-var placement tables
(multi_devices_graph_pass.cc shard assignment + the fleet sharding
strategies). On TPU the whole placement problem reduces to one table of
named-axis PartitionSpecs handed to GSPMD as in/out_shardings.

The ZeRO rule follows "Automatic Cross-Replica Sharding of Weight Update
in Data-Parallel Training" (arxiv 2004.13336): parameters stay replicated
across the data axis (activations/gradients shard on batch), while the
optimizer accumulators — and therefore the weight-update computation that
consumes them — shard their leading dim across the data axis. GSPMD then
emits the reduce-scatter + all-gather decomposition of the gradient
all-reduce automatically. Any dim that does not divide its axis falls back
to replication (SNIPPETS.md [3] naive-sharding rule), so the table always
resolves: every var gets *some* spec.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..monitor import STAT_SET
from ..monitor import enabled as _monitor_on
from .mesh import make_mesh

__all__ = ["SpecLayout", "MeshDims", "mesh_from_spec", "DATA_AXIS",
           "MODEL_AXIS", "FSDP_AXIS"]

DATA_AXIS = "dp"
MODEL_AXIS = "tp"
# Weight-sharding (FSDP) axis, SNIPPETS.md [1]: parameters shard their
# leading dim here (ZeRO-3 — weights, not just optimizer state), and
# GSPMD inserts the per-layer all-gather before each use. Third
# positional axis of mesh_from_spec ("dp,tp,fsdp").
FSDP_AXIS = "fsdp"

# Optimizer accumulator name markers. optimizer._add_accumulator names
# accumulators unique_name.generate(f"{param.name}_{acc}") -> e.g.
# "fc_0.w_0_moment1_0"; these substrings identify the param-shaped
# moments/velocities that the ZeRO rule shards over the data axis.
_ZERO_ACC_MARKERS = (
    "_moment1_", "_moment2_", "_moment_", "_velocity_", "_inf_norm_",
    "_avg_squared_grad_", "_avg_squared_update_", "_mean_square_",
    "_momentum_", "_mean_grad_", "_squared_", "_linear_",
)
# Scalar schedule state: always replicated (shape [1] — never divisible,
# but matching by name avoids even attempting the fallback path).
_SCALAR_MARKERS = ("learning_rate", "_beta1_pow_", "_beta2_pow_")


_POSITIONAL_AXES = (DATA_AXIS, MODEL_AXIS, FSDP_AXIS)


def mesh_axes_for(ndims: int):
    """Positional axis names for an n-dim mesh shape: (dp), (dp, tp),
    (dp, tp, fsdp). Shared by mesh_from_spec and MeshDims so the
    device-backed and device-free spellings can never disagree."""
    if not 1 <= ndims <= len(_POSITIONAL_AXES):
        raise ValueError(
            f"mesh rank {ndims}: expected 'dp', 'dp,tp' or "
            f"'dp,tp,fsdp' (1-{len(_POSITIONAL_AXES)} axes)")
    return _POSITIONAL_AXES[:ndims]


def mesh_from_spec(spec: str, devices=None) -> Mesh:
    """Build a Mesh from a 'dp' / 'dp,tp' / 'dp,tp,fsdp' shape string
    ("8", "4,2", "2,2,2").

    Axis names follow position: first axis is the data axis, second the
    model axis — the Mesh(data, model) convention of docs/sharding.md —
    and third the weight-sharding (FSDP) axis from SNIPPETS.md [1].
    """
    dims = tuple(int(d) for d in str(spec).replace("x", ",").split(",")
                 if str(d).strip())
    if not dims or any(d < 1 for d in dims):
        raise ValueError(
            f"mesh spec {spec!r}: expected 'dp'[,'tp'[,'fsdp']] "
            f"positive ints")
    names = mesh_axes_for(len(dims))
    return make_mesh(shape=dims, axis_names=names, devices=devices)


class MeshDims:
    """Device-free stand-in for jax.sharding.Mesh: axis names + sizes
    only. Static tooling (tools/program_lint.py --memory --mesh) needs
    shard counts on hosts that don't HAVE the dp x tp devices; only
    SpecLayout's spec/shard-count queries work over it (named_sharding
    requires a real Mesh)."""

    def __init__(self, shape, axis_names=None):
        shape = tuple(int(d) for d in shape)
        if axis_names is None:
            axis_names = mesh_axes_for(len(shape)) if shape else ()
        if len(axis_names) != len(shape):
            raise ValueError(f"axis_names {axis_names} vs shape {shape}")
        if any(d < 1 for d in shape):
            raise ValueError(f"mesh shape {shape}: axes must be >= 1")
        self.axis_names = tuple(axis_names)
        self.shape = dict(zip(self.axis_names, shape))
        self.size = int(np.prod(shape)) if shape else 1


class SpecLayout:
    """Var-name -> PartitionSpec table for one program under one mesh.

    Resolution is total: `spec_for` returns a PartitionSpec for ANY
    (name, shape) — the fallback is replication (PartitionSpec()), never
    an error. Built once per (program, mesh); the instance is then both
    the `state_spec_fn` for CompiledProgram.with_distributed (callable
    on a var name) and the shard-count oracle for the memory planner.
    """

    def __init__(self, mesh: Mesh, data_axis: str = DATA_AXIS,
                 model_axis: str = MODEL_AXIS, shard_params: bool = True,
                 fsdp_axis: str = FSDP_AXIS):
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.model_axis = model_axis if model_axis in mesh.axis_names \
            else None
        # fsdp resolution hook (SNIPPETS.md [1], ROADMAP item 1): when
        # the mesh carries this axis, parameters shard their leading
        # dim over it — full weight sharding, not just optimizer state.
        self.fsdp_axis = fsdp_axis if fsdp_axis in mesh.axis_names \
            else None
        self.dp = int(mesh.shape[self.data_axis]) if self.data_axis else 1
        self.tp = int(mesh.shape[self.model_axis]) if self.model_axis \
            else 1
        self.fsdp = int(mesh.shape[self.fsdp_axis]) if self.fsdp_axis \
            else 1
        self.shard_params = shard_params
        self._table: Dict[str, PartitionSpec] = {}
        # Non-divisibility fallbacks: every time a rule WANTED to shard
        # (name, dim) over axis but the dim did not divide, the decline
        # is recorded here — analysis/sharding.py turns these into
        # PTV062 "silently replicated" findings instead of losing them.
        self.fallbacks: list = []
        self._fallback_seen: set = set()

    def _note_fallback(self, name: str, dim: int, axis: str,
                       dim_size, axis_size: int):
        key = (name, dim, axis)
        if key in self._fallback_seen:
            return
        self._fallback_seen.add(key)
        self.fallbacks.append(
            {"name": str(name), "dim": int(dim), "axis": str(axis),
             "dim_size": int(dim_size), "axis_size": int(axis_size)})

    # -- classification --------------------------------------------------
    @staticmethod
    def _is_scalar_state(name: str) -> bool:
        return any(m in name or name.endswith(m.rstrip("_"))
                   for m in _SCALAR_MARKERS)

    @staticmethod
    def _is_zero_accumulator(name: str) -> bool:
        return any(m in name or name.endswith(m.rstrip("_"))
                   for m in _ZERO_ACC_MARKERS)

    # -- spec rules ------------------------------------------------------
    def _model_parts(self, name, shape) -> list:
        """Per-dim axis assignment for the model (tp) axis: last dim of
        a >=2-D tensor, when divisible. [] when tp doesn't apply."""
        parts = [None] * len(shape)
        if (self.shard_params and self.tp > 1 and len(shape) >= 2
                and shape[-1] is not None and shape[-1] > 0):
            if shape[-1] % self.tp == 0:
                parts[-1] = self.model_axis
            else:
                self._note_fallback(name, len(shape) - 1,
                                    self.model_axis, shape[-1], self.tp)
        return parts

    def _fsdp_dim0(self, name, shape, parts) -> list:
        """The fsdp resolution hook: leading dim over the fsdp axis
        when divisible and not already assigned. Applies to any >=1-D
        parameter — embeddings, qkv/ffn weights, 1-D layer_norm scales
        alike (SNIPPETS.md [1] per-family specs all lead with fsdp)."""
        if (self.shard_params and self.fsdp_axis and self.fsdp > 1
                and shape and shape[0] is not None and shape[0] > 0
                and parts[0] is None):
            if shape[0] % self.fsdp == 0:
                parts[0] = self.fsdp_axis
            else:
                self._note_fallback(name, 0, self.fsdp_axis, shape[0],
                                    self.fsdp)
        return parts

    def param_spec(self, name: str, shape: Tuple[int, ...]) -> \
            PartitionSpec:
        """Parameters: replicated over data (ZeRO keeps weights whole
        for the forward pass), last dim over the model axis when it
        divides — the Megatron-style column split GSPMD propagates
        through matmuls — and, when the mesh has an fsdp axis, leading
        dim over fsdp (full weight sharding; GSPMD all-gathers before
        each use)."""
        shape = tuple(s for s in (shape or ()))
        parts = self._fsdp_dim0(name, shape,
                                self._model_parts(name, shape))
        return PartitionSpec(*parts) if any(parts) else PartitionSpec()

    def zero_spec(self, name: str, shape: Tuple[int, ...]) -> \
            PartitionSpec:
        """Optimizer accumulators (arxiv 2004.13336): leading dim over
        the data axis when divisible (plus the same model split as the
        owning param), else fall back toward replication per-dim. With
        an fsdp axis the accumulators co-shard with the weights (fsdp
        on dim 0) instead — the update math stays local either way."""
        shape = tuple(s for s in (shape or ()))
        if not shape:
            return PartitionSpec()
        parts = self._model_parts(name, shape)
        if self.fsdp_axis and self.fsdp > 1:
            parts = self._fsdp_dim0(name, shape, parts)
        elif (self.data_axis and self.dp > 1 and shape[0] is not None
                and shape[0] > 0 and parts[0] is None):
            if shape[0] % self.dp == 0:
                parts[0] = self.data_axis
            else:
                self._note_fallback(name, 0, self.data_axis, shape[0],
                                    self.dp)
        return PartitionSpec(*parts) if any(parts) else PartitionSpec()

    def feed_spec(self, name: str, shape: Tuple[int, ...]) -> \
            PartitionSpec:
        """Feeds shard dim 0 (batch) across the data axis when it
        divides; otherwise replicate (small/odd batches still run)."""
        shape = tuple(s for s in (shape or ()))
        if (self.data_axis and self.dp > 1 and shape
                and shape[0] is not None and shape[0] > 0):
            if shape[0] % self.dp == 0:
                return PartitionSpec(self.data_axis)
            self._note_fallback(name, 0, self.data_axis, shape[0],
                                self.dp)
        return PartitionSpec()

    def spec_for(self, name: str, shape=None,
                 is_param: bool = False) -> PartitionSpec:
        """Total resolution: scalar state -> replicate; optimizer
        accumulator -> ZeRO rule; params -> param rule; everything else
        (activations live inside the jitted step — GSPMD propagates
        them from feeds/params) -> replicate."""
        shape = tuple(shape or ())
        if self._is_scalar_state(name) or not shape or \
                int(np.prod([s or 1 for s in shape])) <= 1:
            return PartitionSpec()
        if self._is_zero_accumulator(name):
            return self.zero_spec(name, shape)
        if is_param or len(shape) >= 2:
            return self.param_spec(name, shape)
        return PartitionSpec()

    # -- table build -----------------------------------------------------
    def add_program(self, program) -> "SpecLayout":
        """Resolve every persistable var in `program` into the table
        (activations are left to GSPMD propagation inside the jit)."""
        sharded = replicated = 0
        for v in program.list_vars():
            if not getattr(v, "persistable", False):
                continue
            spec = self.spec_for(
                v.name, getattr(v, "shape", None) or (),
                is_param=getattr(v, "is_parameter", False))
            self._table[v.name] = spec
            if any(a is not None for a in spec):
                sharded += 1
            else:
                replicated += 1
        if _monitor_on():
            STAT_SET("parallel.sharded_vars", sharded)
            STAT_SET("parallel.replicated_vars", replicated)
            STAT_SET("parallel.mesh_devices", int(self.mesh.size))
        return self

    # -- consumers -------------------------------------------------------
    def __call__(self, name: str) -> Optional[PartitionSpec]:
        """state_spec_fn signature for CompiledProgram.with_distributed:
        None means 'replicated' there, so unknown names resolve safely."""
        spec = self._table.get(name)
        if spec is not None and any(a is not None for a in spec):
            return spec
        return None

    def named_sharding(self, name: str, shape=None) -> NamedSharding:
        spec = self._table.get(name)
        if spec is None:
            spec = self.spec_for(name, shape)
        return NamedSharding(self.mesh, spec)

    def shard_count(self, name: str, shape=None) -> int:
        """How many ways the var's bytes split across the mesh — the
        divisor tools/program_lint.py --memory --mesh applies to the
        per-chip peak-HBM estimate."""
        spec = self._table.get(name)
        if spec is None:
            spec = self.spec_for(name, shape)
        n = 1
        for axes in spec:
            if axes is None:
                continue
            for a in (axes if isinstance(axes, tuple) else (axes,)):
                n *= int(self.mesh.shape[a])
        return n

    def gradient_sync_bytes(self, program) -> int:
        """Closed-form per-step gradient-synchronisation volume: every
        dp-replicated parameter's gradient is all-reduced (2(n-1)/n ~ 2x
        payload in a ring), counted once per step. Sharded-update params
        reduce-scatter + all-gather the same payload, so the estimate
        holds for both layouts (arxiv 2004.13336 §3). Kept as the
        reconciliation reference the per-op cost model must agree with
        (tools/perf_ledger.py's predicted-vs-measured drift rows)."""
        sync_over = self.dp * (self.fsdp
                               if self.fsdp_axis and self.fsdp > 1
                               else 1)
        if sync_over <= 1:
            return 0
        total = 0
        for v in program.list_vars():
            if not getattr(v, "is_parameter", False):
                continue
            shape = tuple(s for s in (getattr(v, "shape", ()) or ())
                          if s and s > 0)
            if not shape:
                continue
            try:
                from ..core.dtypes import as_np_dtype
                itemsize = np.dtype(as_np_dtype(v.dtype)).itemsize
            except Exception:
                itemsize = 4
            nbytes = int(np.prod(shape)) * itemsize
            total += nbytes // self.shard_count(v.name, shape)
        return 2 * total

    def collective_bytes_estimate(self, program) -> int:
        """Static per-step collective-traffic volume — ONE oracle: the
        per-op communication-cost model of analysis/sharding.py (layout
        propagation + priced collectives: gradient all-reduce /
        reduce-scatter+all-gather, explicit c_* ops, implicit
        reshards). The bench sharded path reports this number, and the
        regression tests hold it within 10% of the closed-form
        gradient_sync_bytes above on the bench builders."""
        from ..analysis.sharding import analyze_program_sharding
        return int(analyze_program_sharding(
            program, layout=self).collective_bytes_per_step)

    def to_dict(self) -> Dict[str, str]:
        return {n: str(s) for n, s in sorted(self._table.items())}

    def __len__(self) -> int:
        return len(self._table)

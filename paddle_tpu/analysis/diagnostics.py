"""Diagnostic records, rule catalog, and the verification result type.

Every finding the verifier emits is a `Diagnostic` with a stable rule ID
from `RULES`, a severity, and provenance in the "{op_type}:{block}/
{op_idx}" format shared with FLAGS_op_trace_scopes — the verifier, the
HLO op_name metadata, and the profiler all name an op the same way.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

ERROR = "error"
WARN = "warn"

# Rule catalog: id -> (default severity, one-line description). The IDs
# are stable — tools, tests, and suppression lists key on them; add new
# rules at the end of their band, never renumber. Full catalog with
# examples: docs/static_analysis.md.
RULES = {
    # registry band (00x)
    "PTV001": (ERROR, "op type has no registered lowering"),
    "PTV002": (ERROR, "saved op version newer than this build supports"),
    # dataflow band (01x)
    "PTV010": (ERROR, "op reads a var that is declared nowhere"),
    "PTV011": (ERROR, "op reads a var before any op produces it"),
    "PTV012": (WARN, "op unreachable from the fetch targets (dead)"),
    "PTV013": (WARN, "op output is never read, fetched, or persisted"),
    "PTV014": (WARN, "var overwritten before anything reads it"),
    "PTV015": (WARN, "inplace op aliases a var that a later op reads"),
    # spec band (02x)
    "PTV020": (ERROR, "inferred shape contradicts the declared shape"),
    "PTV021": (ERROR, "inferred dtype contradicts the declared dtype"),
    "PTV022": (ERROR, "abstract evaluation of the lowering failed"),
    # interface band (03x)
    "PTV030": (ERROR, "feed does not match a declared program input"),
    "PTV031": (ERROR, "fetch target is never materialised at top level"),
    # control-flow band (04x)
    "PTV040": (ERROR, "control-flow sub-block reference is inconsistent"),
    # memory band (05x) — the static memory planner (analysis/memory.py)
    "PTV050": (ERROR, "estimated peak HBM exceeds the memory budget"),
    "PTV051": (ERROR, "a single tensor alone exceeds the memory budget"),
    "PTV052": (WARN, "large dead buffers are eligible for reuse"),
    # sharding band (06x) — the static sharding analyzer
    # (analysis/sharding.py)
    "PTV060": (ERROR, "operands disagree on a mesh axis (layout-"
                      "inconsistent op)"),
    "PTV061": (WARN, "implicit reshard on a hot path (per-op resharded "
                     "bytes over threshold)"),
    "PTV062": (WARN, "non-divisible shard dim silently replicated"),
    "PTV063": (WARN, "op has no sharding propagation rule (conservative "
                     "replicate + reshard)"),
}


@dataclasses.dataclass
class Diagnostic:
    rule: str
    message: str
    severity: str = ""          # defaulted from RULES when empty
    op_type: Optional[str] = None
    block: int = 0
    op_idx: Optional[int] = None
    var: Optional[str] = None

    def __post_init__(self):
        if not self.severity:
            self.severity = RULES[self.rule][0]

    @property
    def where(self) -> str:
        """Provenance in the op-trace-scope format; program-level
        findings (feed/fetch checks) have no op to point at."""
        if self.op_type is None:
            return "program"
        idx = "?" if self.op_idx is None else self.op_idx
        return f"{self.op_type}:{self.block}/{idx}"

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "severity": self.severity,
             "where": self.where, "message": self.message}
        if self.var:
            d["var"] = self.var
        return d

    def __str__(self):
        return f"{self.rule} [{self.severity}] at {self.where}: " \
               f"{self.message}"


class ProgramVerificationError(RuntimeError):
    """Raised by FLAGS_program_verify=error before any XLA compile."""

    def __init__(self, result: "VerifyResult"):
        self.result = result
        errs = result.errors()
        shown = "; ".join(str(d) for d in errs[:5])
        more = f" (+{len(errs) - 5} more)" if len(errs) > 5 else ""
        super().__init__(
            f"program verification failed with {len(errs)} error(s): "
            f"{shown}{more} — see docs/static_analysis.md; set "
            f"FLAGS_program_verify=warn|off to bypass")


class VerifyResult:
    """All findings from one `verify_program` call."""

    def __init__(self, findings: Optional[List[Diagnostic]] = None):
        self.findings: List[Diagnostic] = list(findings or [])

    def add(self, rule, message, **kw):
        self.findings.append(Diagnostic(rule, message, **kw))

    def extend(self, other: "VerifyResult"):
        self.findings.extend(other.findings)

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.findings if d.severity == ERROR]

    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.findings if d.severity == WARN]

    def by_rule(self, rule: str) -> List[Diagnostic]:
        return [d for d in self.findings if d.rule == rule]

    @property
    def ok(self) -> bool:
        """True when no error-severity findings (warnings allowed)."""
        return not self.errors()

    def raise_if_errors(self):
        if not self.ok:
            raise ProgramVerificationError(self)

    def summary(self) -> str:
        e, w = self.errors(), self.warnings()
        if not self.findings:
            return "program verification: clean"
        shown = "; ".join(str(d) for d in (e + w)[:3])
        more = len(self.findings) - min(3, len(self.findings))
        tail = f" (+{more} more)" if more else ""
        return (f"program verification: {len(e)} error(s), "
                f"{len(w)} warning(s): {shown}{tail}")

    def to_dict(self) -> dict:
        return {"ok": self.ok,
                "counts": {"error": len(self.errors()),
                           "warn": len(self.warnings())},
                "findings": [d.to_dict() for d in self.findings]}

    def __repr__(self):
        return (f"VerifyResult({len(self.errors())} errors, "
                f"{len(self.warnings())} warnings)")

"""Graph lints + the FLAGS_program_verify pre-compile gate.

`verify_program` is the pure entry point (CLI, tests); `verify_gate` is
the memoized wrapper Executor.run and ServingEngine.warmup call so a
program is verified once per (fingerprint, feeds, fetches) and never
again — the expensive half (abstract evaluation of every lowering,
shape_infer.py) is additionally memoized by fingerprint alone, so
re-running one program with different fetch lists only repeats the cheap
graph walks.

Rule catalog: diagnostics.RULES / docs/static_analysis.md.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Iterable, Optional

from ..core.registry import REGISTRY
from ..monitor import STAT_ADD
from .diagnostics import VerifyResult
from .graph_utils import (CTRL_FLOW_SUB_BLOCK as _CTRL_FLOW_SUB_BLOCK,
                          SIDE_EFFECT_OPS as _SIDE_EFFECT_OPS,
                          available_at_entry, live_op_mask,
                          op_names as _op_names, program_read_names,
                          scan_block_hazards)
from .shape_infer import OPAQUE_OPS, declared_spec, infer_program_specs

__all__ = ["verify_program", "verify_gate"]


def verify_program(program, feed_names: Optional[Iterable[str]] = None,
                   fetch_names: Optional[Iterable[str]] = None,
                   op_versions: Optional[Dict[str, int]] = None,
                   check_shapes: bool = True,
                   _core: Optional[VerifyResult] = None) -> VerifyResult:
    """Statically verify `program`; no compilation, no device work.

    feed_names: vars supplied at run time (beyond is_data/persistable
    vars) — counted as available for the dataflow lints and checked to
    exist (PTV030). fetch_names: enables dead-op reachability (PTV012)
    and the fetch-materialisation check (PTV031). op_versions: a saved
    program's {op type: version} map, checked against the registry
    (PTV002). check_shapes=False skips the abstract-evaluation pass.
    """
    feed_set = {str(n) for n in (feed_names or ())}
    fetch_list = [str(n) for n in (fetch_names or ())]

    result = VerifyResult()
    if _core is not None:
        result.extend(_core)
    else:
        result.extend(_verify_core(program, check_shapes))

    if op_versions:
        _lint_versions(op_versions, result)
    _lint_io(program, feed_set, fetch_list, result)
    if fetch_list:
        _lint_dead_ops(program, fetch_list, result)
    _lint_unused_outputs(program, fetch_list, result)
    return result


def _verify_core(program, check_shapes=True) -> VerifyResult:
    """The feed/fetch-independent findings (memoizable by fingerprint)."""
    result = VerifyResult()
    for block in program.blocks:
        _lint_block(program, block, result)
    if check_shapes:
        infer_program_specs(program, result)
    return result


# ---------------------------------------------------------------------------
# per-block dataflow lints
# ---------------------------------------------------------------------------

def _lint_block(program, block, result):
    avail = available_at_entry(program, block)

    for op_idx, op in enumerate(block.ops):
        opdef = REGISTRY._ops.get(op.type)
        if opdef is None:
            import difflib
            close = difflib.get_close_matches(
                op.type, list(REGISTRY._ops), n=3, cutoff=0.6)
            hint = ("; did you mean " +
                    ", ".join(repr(c) for c in close) + "?") if close \
                else ""
            result.add("PTV001",
                       f"op type {op.type!r} has no registered "
                       f"lowering{hint}",
                       op_type=op.type, block=block.idx, op_idx=op_idx)

        ins = list(_op_names(op, "in"))
        outs = list(_op_names(op, "out"))

        for name in ins:
            var = block._find_var_recursive(name)
            if var is None:
                result.add("PTV010",
                           f"input {name!r} is not declared in block "
                           f"{block.idx} or any ancestor",
                           op_type=op.type, block=block.idx,
                           op_idx=op_idx, var=name)
            elif name not in avail and name not in outs:
                result.add("PTV011",
                           f"input {name!r} is read before any op "
                           f"produces it (not persistable, not a data "
                           f"var, not fed)",
                           op_type=op.type, block=block.idx,
                           op_idx=op_idx, var=name)
        for name in outs:
            avail.add(name)

        if op.type in _CTRL_FLOW_SUB_BLOCK:
            _lint_sub_block(program, block, op, op_idx, result)

    # WAW / inplace-alias findings come from the shared scan the
    # donation planner also consumes (analysis/graph_utils.py) — lint
    # and rewrite must agree on what is hazardous.
    waw, alias_reads, _ = scan_block_hazards(block)
    for op_idx, op_type, name, p_idx, p_type in waw:
        result.add("PTV014",
                   f"{name!r} written by {p_type!r} (op {p_idx}) is "
                   f"overwritten before anything reads it",
                   op_type=op_type, block=block.idx, op_idx=op_idx,
                   var=name)
    for op_idx, op_type, name, w_idx, w_type in alias_reads:
        result.add("PTV015",
                   f"{name!r} was updated in place by {w_type!r} (op "
                   f"{w_idx}) but is read again here — the buffer may "
                   f"be donated/overwritten",
                   op_type=op_type, block=block.idx, op_idx=op_idx,
                   var=name)


def _lint_sub_block(program, block, op, op_idx, result):
    def bad(msg):
        result.add("PTV040", msg, op_type=op.type, block=block.idx,
                   op_idx=op_idx)

    sb = op.attrs.get("sub_block")
    if isinstance(sb, dict):  # {"__block__": idx} serialized form
        sb = sb.get("__block__")
    if not isinstance(sb, int) or not (0 < sb < len(program.blocks)):
        bad(f"sub_block attr {op.attrs.get('sub_block')!r} does not "
            f"name a block of this program "
            f"({len(program.blocks)} blocks)")
        return
    sub = program.blocks[sb]
    for attr in ("output_vars", "carried_vars", "input_vars"):
        for name in op.attrs.get(attr, []) or []:
            if sub._find_var_recursive(name) is None:
                bad(f"{attr} entry {name!r} is not declared in "
                    f"sub-block {sb} or its ancestors")
    cond = op.attrs.get("condition")
    if op.type == "while" and cond \
            and sub._find_var_recursive(cond) is None:
        bad(f"condition var {cond!r} is not declared in sub-block "
            f"{sb} or its ancestors")


# ---------------------------------------------------------------------------
# program-level lints
# ---------------------------------------------------------------------------

def _lint_versions(saved: Dict[str, int], result):
    for t, v in saved.items():
        if REGISTRY.has(t) and int(v) > REGISTRY.get(t).version:
            result.add("PTV002",
                       f"saved program uses {t!r} v{v} but this build "
                       f"supports v{REGISTRY.get(t).version}",
                       op_type=t)


def _lint_io(program, feed_set, fetch_list, result):
    gb = program.global_block()
    for name in sorted(feed_set):
        if not gb.has_var(name):
            result.add("PTV030",
                       f"feed {name!r} does not name a var of the "
                       f"program", var=name)
    if not fetch_list:
        return
    produced = {n for op in gb.ops for n in _op_names(op, "out")}
    for name in fetch_list:
        var = gb._find_var_recursive(name)
        if var is None:
            result.add("PTV031",
                       f"fetch target {name!r} does not name a var of "
                       f"the program", var=name)
        elif name not in produced and not var.persistable \
                and not var.is_data and name not in feed_set:
            result.add("PTV031",
                       f"fetch target {name!r} is never produced in the "
                       f"global block (sub-block values do not surface)",
                       var=name)


def _lint_dead_ops(program, fetch_list, result):
    # shared walk: the False entries here are exactly what the DCE pass
    # removes (analysis/passes/dce.py)
    block = program.global_block()
    mask = live_op_mask(program, fetch_list)
    for op_idx, live in enumerate(mask):
        if not live:
            op = block.ops[op_idx]
            outs = _op_names(op, "out")
            result.add("PTV012",
                       f"no path from its outputs {outs} to the fetch "
                       f"targets — op never affects a fetched value",
                       op_type=op.type, block=block.idx, op_idx=op_idx)


def _lint_unused_outputs(program, fetch_list, result):
    # one shared definition of "read" (graph_utils.program_read_names):
    # op inputs + attr-carried names of EVERY block, so a var whose
    # only reader sits in a (possibly nested) while/conditional_block
    # sub-block counts as used — same rule the memory planner's
    # liveness and the DCE reachability apply
    reads = set(fetch_list) | program_read_names(program)
    for blk in program.blocks:
        for op_idx, op in enumerate(blk.ops):
            if op.type in _SIDE_EFFECT_OPS or op.type in OPAQUE_OPS:
                continue
            outs = list(_op_names(op, "out"))
            if len(outs) < 2:
                # single-output dead ops are PTV012's job; flagging every
                # unfetched tail value would be noise
                continue
            for name in outs:
                v = blk._find_var_recursive(name)
                if v is not None and (v.persistable or v.is_data):
                    continue
                if name not in reads:
                    result.add("PTV013",
                               f"output {name!r} is never read, "
                               f"fetched, or persisted (auxiliary "
                               f"output that could be dropped)",
                               op_type=op.type, block=blk.idx,
                               op_idx=op_idx, var=name)


# ---------------------------------------------------------------------------
# the pre-compile gate (Executor.run / ServingEngine.warmup)
# ---------------------------------------------------------------------------

_MEMO_LOCK = threading.Lock()
_CORE_MEMO: "OrderedDict[str, VerifyResult]" = OrderedDict()
_GATE_MEMO: "OrderedDict[tuple, VerifyResult]" = OrderedDict()
_MEMO_CAP = 256


def _memo_put(memo, key, val):
    memo[key] = val
    while len(memo) > _MEMO_CAP:
        memo.popitem(last=False)


def reset_memo():
    """Drop gate memoization (tests; after re-registering ops)."""
    with _MEMO_LOCK:
        _CORE_MEMO.clear()
        _GATE_MEMO.clear()


def verify_gate(program, feed_names=None, fetch_names=None,
                where="executor") -> Optional[VerifyResult]:
    """The FLAGS_program_verify gate: off | warn (default) | error.

    Runs verify_program once per (program fingerprint, feed names,
    fetch names) and memoizes; in 'error' mode error-severity findings
    raise ProgramVerificationError — BEFORE any executable is built or
    cached, so Executor.cache_stats() shows zero misses for a rejected
    program. In 'warn' mode findings surface as a single summarized
    warnings.warn per program."""
    from ..core.flags import FLAGS
    mode = FLAGS.program_verify
    if mode == "off":
        return None
    if mode not in ("warn", "error"):
        raise ValueError(
            f"FLAGS_program_verify={mode!r}: expected 'off', 'warn' or "
            f"'error'")

    fp = program.fingerprint()
    key = (fp, tuple(sorted(str(n) for n in (feed_names or ()))),
           tuple(str(n) for n in (fetch_names or ())))
    with _MEMO_LOCK:
        res = _GATE_MEMO.get(key)
        core = _CORE_MEMO.get(fp)
    fresh = res is None
    if fresh:
        if core is None:
            core = _verify_core(program)
            with _MEMO_LOCK:
                _memo_put(_CORE_MEMO, fp, core)
        res = verify_program(program, feed_names=key[1],
                             fetch_names=key[2], _core=core)
        with _MEMO_LOCK:
            _memo_put(_GATE_MEMO, key, res)
        STAT_ADD("analysis.programs_verified")
        if res.errors():
            STAT_ADD("analysis.findings_error", len(res.errors()))
        if res.warnings():
            STAT_ADD("analysis.findings_warn", len(res.warnings()))
    if mode == "error":
        res.raise_if_errors()
    elif fresh and res.findings:
        import warnings
        warnings.warn(f"[{where}] {res.summary()} "
                      f"(FLAGS_program_verify=warn; see "
                      f"docs/static_analysis.md)")
    return res

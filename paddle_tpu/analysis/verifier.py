"""Graph lints + the FLAGS_program_verify pre-compile gate.

`verify_program` is the pure entry point (CLI, tests); `verify_gate` is
the memoized wrapper Executor.run and ServingEngine.warmup call so a
program is verified once per (fingerprint, feeds, fetches) and never
again — the expensive half (abstract evaluation of every lowering,
shape_infer.py) is additionally memoized by fingerprint alone, so
re-running one program with different fetch lists only repeats the cheap
graph walks.

Rule catalog: diagnostics.RULES / docs/static_analysis.md.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Iterable, Optional

from ..core.registry import REGISTRY
from ..monitor import STAT_ADD
from .diagnostics import VerifyResult
from .shape_infer import OPAQUE_OPS, declared_spec, infer_program_specs

__all__ = ["verify_program", "verify_gate"]

# Ops whose execution is the point (host effects), so dead-op
# reachability never flags them even when nothing reads their outputs.
_SIDE_EFFECT_OPS = frozenset({
    "print", "save", "save_combine", "load", "load_combine",
    "feed", "fetch", "read", "create_custom_reader", "py_func",
    "send", "recv", "prefetch", "fetch_barrier", "send_barrier",
    "checkpoint_notify", "geo_sgd_send", "distributed_notify",
    "listen_and_serv", "fl_listen_and_serv", "delete_var",
    "push_box_sparse", "gen_nccl_id", "c_gen_nccl_id", "c_comm_init",
    "c_comm_init_all", "c_sync_calc_stream", "c_sync_comm_stream",
})

# Control-flow ops that legitimately re-write a var another op already
# wrote (branch merge / carry patterns) — excluded from write-after-write.
_MERGE_OPS = frozenset({
    "conditional_block", "conditional_block_infer", "while",
    "select_input", "merge_lod_tensor", "assign", "recurrent",
})

_CTRL_FLOW_SUB_BLOCK = ("while", "conditional_block",
                        "conditional_block_infer", "recurrent",
                        "recompute_segment")


def _op_names(op, which) -> Iterable[str]:
    d = op.inputs if which == "in" else op.outputs
    return [n for ns in d.values() for n in ns if n]


def verify_program(program, feed_names: Optional[Iterable[str]] = None,
                   fetch_names: Optional[Iterable[str]] = None,
                   op_versions: Optional[Dict[str, int]] = None,
                   check_shapes: bool = True,
                   _core: Optional[VerifyResult] = None) -> VerifyResult:
    """Statically verify `program`; no compilation, no device work.

    feed_names: vars supplied at run time (beyond is_data/persistable
    vars) — counted as available for the dataflow lints and checked to
    exist (PTV030). fetch_names: enables dead-op reachability (PTV012)
    and the fetch-materialisation check (PTV031). op_versions: a saved
    program's {op type: version} map, checked against the registry
    (PTV002). check_shapes=False skips the abstract-evaluation pass.
    """
    feed_set = {str(n) for n in (feed_names or ())}
    fetch_list = [str(n) for n in (fetch_names or ())]

    result = VerifyResult()
    if _core is not None:
        result.extend(_core)
    else:
        result.extend(_verify_core(program, check_shapes))

    if op_versions:
        _lint_versions(op_versions, result)
    _lint_io(program, feed_set, fetch_list, result)
    if fetch_list:
        _lint_dead_ops(program, fetch_list, result)
    _lint_unused_outputs(program, fetch_list, result)
    return result


def _verify_core(program, check_shapes=True) -> VerifyResult:
    """The feed/fetch-independent findings (memoizable by fingerprint)."""
    result = VerifyResult()
    for block in program.blocks:
        _lint_block(program, block, result)
    if check_shapes:
        infer_program_specs(program, result)
    return result


# ---------------------------------------------------------------------------
# per-block dataflow lints
# ---------------------------------------------------------------------------

def _available_at_entry(program, block):
    """Vars readable before any op of `block` runs: the whole ancestor
    scope chain (sub-blocks are entered mid-parent, and shapes are
    static, so the parent's full symbol table is a sound
    over-approximation) plus local persistables/data vars."""
    avail = set()
    blk = block
    while blk is not None:
        if blk is block:
            avail |= {n for n, v in blk.vars.items()
                      if v.persistable or v.is_data}
        else:
            avail |= set(blk.vars)
        blk = blk.parent
    return avail


def _lint_block(program, block, result):
    avail = _available_at_entry(program, block)
    last_write = {}   # var -> (op_idx, op_type, is_merge_or_inplace)
    inplace_aliases = []  # (op_idx, op_type, var)

    for op_idx, op in enumerate(block.ops):
        opdef = REGISTRY._ops.get(op.type)
        if opdef is None:
            import difflib
            close = difflib.get_close_matches(
                op.type, list(REGISTRY._ops), n=3, cutoff=0.6)
            hint = ("; did you mean " +
                    ", ".join(repr(c) for c in close) + "?") if close \
                else ""
            result.add("PTV001",
                       f"op type {op.type!r} has no registered "
                       f"lowering{hint}",
                       op_type=op.type, block=block.idx, op_idx=op_idx)

        ins = list(_op_names(op, "in"))
        outs = list(_op_names(op, "out"))

        for name in ins:
            var = block._find_var_recursive(name)
            if var is None:
                result.add("PTV010",
                           f"input {name!r} is not declared in block "
                           f"{block.idx} or any ancestor",
                           op_type=op.type, block=block.idx,
                           op_idx=op_idx, var=name)
            elif name not in avail and name not in outs:
                result.add("PTV011",
                           f"input {name!r} is read before any op "
                           f"produces it (not persistable, not a data "
                           f"var, not fed)",
                           op_type=op.type, block=block.idx,
                           op_idx=op_idx, var=name)
            # inplace-alias hazard: a later read of a var an inplace op
            # aliased means donation may have already clobbered it
            for w_idx, w_type, w_var in inplace_aliases:
                if name == w_var:
                    result.add("PTV015",
                               f"{w_var!r} was updated in place by "
                               f"{w_type!r} (op {w_idx}) but is read "
                               f"again here — the buffer may be donated"
                               f"/overwritten",
                               op_type=op.type, block=block.idx,
                               op_idx=op_idx, var=name)
            if name in last_write:
                last_write.pop(name, None)

        is_inplace = bool(opdef is not None and opdef.inplace)
        is_merge = op.type in _MERGE_OPS
        for name in outs:
            var = block._find_var_recursive(name)
            persistable = bool(var is not None and var.persistable)
            prev = last_write.get(name)
            if prev is not None and not persistable \
                    and not (is_inplace or is_merge):
                p_idx, p_type, p_soft = prev
                if not p_soft:
                    result.add("PTV014",
                               f"{name!r} written by {p_type!r} (op "
                               f"{p_idx}) is overwritten before "
                               f"anything reads it",
                               op_type=op.type, block=block.idx,
                               op_idx=op_idx, var=name)
            last_write[name] = (op_idx, op.type,
                                is_inplace or is_merge or persistable)
            avail.add(name)
            if is_inplace and name in ins:
                inplace_aliases.append((op_idx, op.type, name))

        if op.type in _CTRL_FLOW_SUB_BLOCK:
            _lint_sub_block(program, block, op, op_idx, result)


def _lint_sub_block(program, block, op, op_idx, result):
    def bad(msg):
        result.add("PTV040", msg, op_type=op.type, block=block.idx,
                   op_idx=op_idx)

    sb = op.attrs.get("sub_block")
    if isinstance(sb, dict):  # {"__block__": idx} serialized form
        sb = sb.get("__block__")
    if not isinstance(sb, int) or not (0 < sb < len(program.blocks)):
        bad(f"sub_block attr {op.attrs.get('sub_block')!r} does not "
            f"name a block of this program "
            f"({len(program.blocks)} blocks)")
        return
    sub = program.blocks[sb]
    for attr in ("output_vars", "carried_vars", "input_vars"):
        for name in op.attrs.get(attr, []) or []:
            if sub._find_var_recursive(name) is None:
                bad(f"{attr} entry {name!r} is not declared in "
                    f"sub-block {sb} or its ancestors")
    cond = op.attrs.get("condition")
    if op.type == "while" and cond \
            and sub._find_var_recursive(cond) is None:
        bad(f"condition var {cond!r} is not declared in sub-block "
            f"{sb} or its ancestors")


# ---------------------------------------------------------------------------
# program-level lints
# ---------------------------------------------------------------------------

def _lint_versions(saved: Dict[str, int], result):
    for t, v in saved.items():
        if REGISTRY.has(t) and int(v) > REGISTRY.get(t).version:
            result.add("PTV002",
                       f"saved program uses {t!r} v{v} but this build "
                       f"supports v{REGISTRY.get(t).version}",
                       op_type=t)


def _lint_io(program, feed_set, fetch_list, result):
    gb = program.global_block()
    for name in sorted(feed_set):
        if not gb.has_var(name):
            result.add("PTV030",
                       f"feed {name!r} does not name a var of the "
                       f"program", var=name)
    if not fetch_list:
        return
    produced = {n for op in gb.ops for n in _op_names(op, "out")}
    for name in fetch_list:
        var = gb._find_var_recursive(name)
        if var is None:
            result.add("PTV031",
                       f"fetch target {name!r} does not name a var of "
                       f"the program", var=name)
        elif name not in produced and not var.persistable \
                and not var.is_data and name not in feed_set:
            result.add("PTV031",
                       f"fetch target {name!r} is never produced in the "
                       f"global block (sub-block values do not surface)",
                       var=name)


def _op_is_anchored(op, block):
    """Ops kept live regardless of fetch reachability: host effects,
    in-place state updates, writes to persistable vars, opless sinks."""
    if op.type in _SIDE_EFFECT_OPS:
        return True
    opdef = REGISTRY._ops.get(op.type)
    if opdef is not None and opdef.inplace:
        return True
    outs = list(_op_names(op, "out"))
    if not outs:
        return True
    for n in outs:
        v = block._find_var_recursive(n)
        if v is not None and v.persistable:
            return True
    return False


def _lint_dead_ops(program, fetch_list, result):
    block = program.global_block()
    needed = set(fetch_list)
    # lengths companions are read implicitly by the feed path
    needed |= set(program.lod_link.values())
    for op_idx in reversed(range(len(block.ops))):
        op = block.ops[op_idx]
        outs = _op_names(op, "out")
        live = _op_is_anchored(op, block) or any(n in needed
                                                 for n in outs)
        if live:
            needed |= set(_op_names(op, "in"))
            # sub-block reads count: condition/carried vars resolve
            # against the parent scope too
            for attr in ("input_vars", "carried_vars", "condition"):
                v = op.attrs.get(attr)
                if isinstance(v, str):
                    needed.add(v)
                elif isinstance(v, (list, tuple)):
                    needed |= {str(x) for x in v}
            if op.type in _CTRL_FLOW_SUB_BLOCK:
                sb = op.attrs.get("sub_block")
                if isinstance(sb, int) and 0 < sb < len(program.blocks):
                    for sop in program.blocks[sb].ops:
                        needed |= set(_op_names(sop, "in"))
        else:
            result.add("PTV012",
                       f"no path from its outputs {outs} to the fetch "
                       f"targets — op never affects a fetched value",
                       op_type=op.type, block=block.idx, op_idx=op_idx)


def _lint_unused_outputs(program, fetch_list, result):
    reads = set(fetch_list)
    reads |= set(program.lod_link.values())
    for blk in program.blocks:
        for op in blk.ops:
            reads |= set(_op_names(op, "in"))
            for attr in ("input_vars", "carried_vars", "condition",
                         "output_vars"):
                v = op.attrs.get(attr)
                if isinstance(v, str):
                    reads.add(v)
                elif isinstance(v, (list, tuple)):
                    reads |= {str(x) for x in v}
    for blk in program.blocks:
        for op_idx, op in enumerate(blk.ops):
            if op.type in _SIDE_EFFECT_OPS or op.type in OPAQUE_OPS:
                continue
            outs = list(_op_names(op, "out"))
            if len(outs) < 2:
                # single-output dead ops are PTV012's job; flagging every
                # unfetched tail value would be noise
                continue
            for name in outs:
                v = blk._find_var_recursive(name)
                if v is not None and (v.persistable or v.is_data):
                    continue
                if name not in reads:
                    result.add("PTV013",
                               f"output {name!r} is never read, "
                               f"fetched, or persisted (auxiliary "
                               f"output that could be dropped)",
                               op_type=op.type, block=blk.idx,
                               op_idx=op_idx, var=name)


# ---------------------------------------------------------------------------
# the pre-compile gate (Executor.run / ServingEngine.warmup)
# ---------------------------------------------------------------------------

_MEMO_LOCK = threading.Lock()
_CORE_MEMO: "OrderedDict[str, VerifyResult]" = OrderedDict()
_GATE_MEMO: "OrderedDict[tuple, VerifyResult]" = OrderedDict()
_MEMO_CAP = 256


def _memo_put(memo, key, val):
    memo[key] = val
    while len(memo) > _MEMO_CAP:
        memo.popitem(last=False)


def reset_memo():
    """Drop gate memoization (tests; after re-registering ops)."""
    with _MEMO_LOCK:
        _CORE_MEMO.clear()
        _GATE_MEMO.clear()


def verify_gate(program, feed_names=None, fetch_names=None,
                where="executor") -> Optional[VerifyResult]:
    """The FLAGS_program_verify gate: off | warn (default) | error.

    Runs verify_program once per (program fingerprint, feed names,
    fetch names) and memoizes; in 'error' mode error-severity findings
    raise ProgramVerificationError — BEFORE any executable is built or
    cached, so Executor.cache_stats() shows zero misses for a rejected
    program. In 'warn' mode findings surface as a single summarized
    warnings.warn per program."""
    from ..core.flags import FLAGS
    mode = FLAGS.program_verify
    if mode == "off":
        return None
    if mode not in ("warn", "error"):
        raise ValueError(
            f"FLAGS_program_verify={mode!r}: expected 'off', 'warn' or "
            f"'error'")

    fp = program.fingerprint()
    key = (fp, tuple(sorted(str(n) for n in (feed_names or ()))),
           tuple(str(n) for n in (fetch_names or ())))
    with _MEMO_LOCK:
        res = _GATE_MEMO.get(key)
        core = _CORE_MEMO.get(fp)
    fresh = res is None
    if fresh:
        if core is None:
            core = _verify_core(program)
            with _MEMO_LOCK:
                _memo_put(_CORE_MEMO, fp, core)
        res = verify_program(program, feed_names=key[1],
                             fetch_names=key[2], _core=core)
        with _MEMO_LOCK:
            _memo_put(_GATE_MEMO, key, res)
        STAT_ADD("analysis.programs_verified")
        if res.errors():
            STAT_ADD("analysis.findings_error", len(res.errors()))
        if res.warnings():
            STAT_ADD("analysis.findings_warn", len(res.warnings()))
    if mode == "error":
        res.raise_if_errors()
    elif fresh and res.findings:
        import warnings
        warnings.warn(f"[{where}] {res.summary()} "
                      f"(FLAGS_program_verify=warn; see "
                      f"docs/static_analysis.md)")
    return res

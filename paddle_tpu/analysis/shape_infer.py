"""Whole-program shape & dtype propagation with zero device work.

Reference analogue: the ~500 hand-written InferShape functions the
reference runs over every OpDesc (framework/operator.h:430). Here the
lowering IS the shape function: each op is abstract-evaluated with
`jax.eval_shape` over its registered lowering — the same trick
`lowering.infer_op_shapes` plays at append time, extended to propagate
through a whole Program (including ops appended with infer_shape=False,
e.g. the grad::generic ops backward.py emits) and to CHECK the inferred
specs against the declared Variable.shape/dtype instead of writing them
back.

Ops that cannot abstract-eval are handled two ways:

- `OpDef.abstract_eval` (core/registry.py): a registered shape rule
  `fn(op, in_specs, block) -> {out_name: (shape, dtype)}` — control-flow
  ops (while, conditional_block) register one in ops/controlflow.py.
- `OPAQUE_OPS`: host/RPC/IO/LoD-array/collective ops whose outputs take
  their declared specs unchecked (the spec-band rules simply do not fire
  for them; the dataflow lints in verifier.py still do).

A spec is `(shape, dtype_name)` with -1 marking dynamic dims. Declared
shapes of `None` or `()` are treated as unknown — `Variable.to_dict`
serializes None as [], so a round-tripped unknown is indistinguishable
from a scalar; treating both as unknown forfeits checking on true
scalars but can never produce a false positive.
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import numpy as np

from ..core.dtypes import as_np_dtype
from ..core.registry import REGISTRY
from ..core import lowering


class Spec(NamedTuple):
    """(shape, dtype_name) with -1 marking dynamic dims.

    A NamedTuple so the historical plain-tuple protocol still holds —
    `shape, dtype = spec`, equality against `(shape, dtype)`, and plain
    tuples returned by abstract_eval rules all keep working; consumers
    that need methods normalize with `Spec(*spec)`.
    """

    shape: Tuple[int, ...]
    dtype: str

    def nbytes(self, dyn_defaults: int = 1) -> Tuple[int, bool]:
        """Size in bytes -> (nbytes, dynamic).

        Dynamic dims (-1, or the _DYN_DIM placeholder family) are
        substituted with `dyn_defaults` elements each, so with the
        default of 1 the returned byte count is a documented LOWER
        BOUND whenever `dynamic` is True. Callers doing budget math
        (PTV050) must surface the marker instead of presenting the
        bound as exact; resolving real feed shapes first (the memory
        gate's seed path) clears the marker.
        """
        dynamic = False
        n = 1
        for d in self.shape:
            d = int(d)
            if d < 0 or d >= _DYN:
                dynamic = True
                d = int(dyn_defaults)
            n *= max(d, 0)
        itemsize = np.dtype(as_np_dtype(self.dtype)).itemsize
        return n * itemsize, dynamic


# Dynamic-dim placeholder shared with lowering.infer_op_shapes: dims this
# large (or products thereof) read back as dynamic.
_DYN = lowering._DYN_DIM

# Ops whose lowering needs runtime machinery an abstract env cannot
# supply: TensorArray vars hold Python lists (not ShapeDtypeStructs),
# host/RPC/IO ops talk to the outside world, mesh collectives need bound
# axis names. Their outputs take declared specs unchecked.
OPAQUE_OPS = frozenset({
    # executor plumbing
    "feed", "fetch",
    # TensorArray / LoD / decode-loop ops (env values are host lists)
    "write_to_array", "read_from_array", "tensor_array_to_tensor",
    "lod_array_length", "array_to_lod_tensor", "lod_tensor_to_array",
    "merge_lod_tensor", "split_lod_tensor", "lod_rank_table",
    "max_sequence_len", "shrink_rnn_memory", "rnn_memory_helper",
    "reorder_lod_tensor_by_rank", "beam_search", "beam_search_decode",
    "beam_reorder", "gather_tree", "select_input",
    # host-side PS/RPC runtime ops
    "listen_and_serv", "fl_listen_and_serv", "send", "recv", "prefetch",
    "fetch_barrier", "send_barrier", "gen_nccl_id", "c_gen_nccl_id",
    "c_comm_init", "c_comm_init_all", "checkpoint_notify",
    "geo_sgd_send", "ref_by_trainer_id", "distributed_lookup_table",
    "lookup_sparse_table", "split_ids", "merge_ids", "split_byref",
    "delete_var", "distributed_notify", "push_box_sparse",
    # host IO / readers
    "save", "save_combine", "load", "load_combine", "read",
    "create_custom_reader",
    # mesh collectives (axis names unbound outside shard_map)
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "c_allgather", "c_reducescatter", "c_broadcast",
    "c_sync_calc_stream", "c_sync_comm_stream", "allreduce", "broadcast",
    "shard_hint", "ring_attention", "ulysses_attention", "c_alltoall",
    "moe_ffn", "sync_batch_norm",
    # misc host-side
    "py_func", "get_places", "fake_init", "coalesce_tensor",
    "recurrent", "recompute_segment", "conditional_block_infer",
    "split_selected_rows", "merge_selected_rows",
    "get_tensor_from_selected_rows",
})


def declared_spec(var) -> Optional[Spec]:
    """(shape, dtype) from a Variable's declaration, None if unknown."""
    shp = getattr(var, "shape", None)
    if not shp:  # None or () — see module docstring
        return None
    return Spec(tuple(int(d) for d in shp), str(var.dtype))


def _dtype_name(dt) -> str:
    import jax.numpy as jnp
    return "bfloat16" if dt == jnp.bfloat16 else str(np.dtype(dt))


def _canon(dtype_name: str):
    return np.dtype(jax.dtypes.canonicalize_dtype(as_np_dtype(dtype_name)))


def _dims_match(inferred, declared) -> bool:
    if len(inferred) != len(declared):
        return False
    for a, b in zip(inferred, declared):
        # -1 and _DYN-derived dims are wildcards on either side
        if a < 0 or b < 0 or a >= _DYN or b >= _DYN:
            continue
        if int(a) != int(b):
            return False
    return True


def _eval_op(op, in_specs: Dict[str, Spec]) -> Dict[str, Spec]:
    """Abstract-evaluate one op's lowering: {in name: spec} -> {out
    name: spec}. Raises whatever the lowering raises under eval_shape."""
    env = {}
    for n, (shape, dtype) in in_specs.items():
        shp = tuple(_DYN if d == -1 else int(d) for d in shape)
        env[n] = jax.ShapeDtypeStruct(shp, as_np_dtype(dtype))

    def f(e):
        e = dict(e)
        ctx = lowering.LowerCtx(jax.random.PRNGKey(0))
        lowering.run_op(op, e, ctx)
        return {n: e[n] for n in op.output_names() if n and n in e}

    out = jax.eval_shape(f, env)
    specs = {}
    for name, sds in out.items():
        shape = tuple(-1 if d >= _DYN else int(d) for d in sds.shape)
        specs[name] = Spec(shape, _dtype_name(sds.dtype))
    return specs


def infer_program_specs(program, result, check=True,
                        seed: Optional[Dict[str, Spec]] = None
                        ) -> Dict[str, Spec]:
    """Propagate specs through every block; append PTV020/021/022
    findings to `result`. Returns the global block's final spec env.

    seed: {var name: (shape, dtype)} pre-loaded into the global block's
    env before propagation — the memory gate seeds the concrete feed
    shapes here so dynamic (-1/_DYN_DIM) dims resolve downstream
    instead of poisoning size arithmetic (Spec.nbytes)."""
    envs: Dict[int, Dict[str, Spec]] = {}
    for block in program.blocks:
        parent = envs.get(block.parent_idx, {}) \
            if block.parent_idx >= 0 else {}
        env = dict(parent)
        if block.idx == 0 and seed:
            for name, spec in seed.items():
                env[str(name)] = Spec(tuple(int(d) for d in spec[0]),
                                      str(spec[1]))
        envs[block.idx] = env
        for op_idx, op in enumerate(block.ops):
            _infer_op(op, op_idx, block, env, result, check)
    return envs.get(0, {})


def _seed_outputs_from_decl(op, block, env):
    for name in op.output_names():
        if not name or name in env:
            continue
        var = block._find_var_recursive(name)
        spec = declared_spec(var) if var is not None else None
        if spec is not None:
            env[name] = spec


def _infer_op(op, op_idx, block, env, result, check):
    opdef = REGISTRY._ops.get(op.type)
    if opdef is None or op.type in OPAQUE_OPS:
        # unregistered is the verifier's PTV001; opaque is by design —
        # either way outputs take declared specs so propagation continues
        _seed_outputs_from_decl(op, block, env)
        return

    in_specs: Dict[str, Spec] = {}
    missing = False
    for name in op.input_names():
        if not name or name in in_specs:
            continue
        spec = env.get(name)
        if spec is None:
            var = block._find_var_recursive(name)
            spec = declared_spec(var) if var is not None else None
        if spec is None:
            missing = True
            break
        in_specs[name] = spec

    if getattr(opdef, "abstract_eval", None) is not None:
        try:
            out = opdef.abstract_eval(op, in_specs, block) or {}
        except Exception as e:  # noqa: BLE001 — a broken rule is a finding
            result.add("PTV022",
                       f"abstract-eval rule for {op.type!r} failed: "
                       f"{type(e).__name__}: {e}",
                       op_type=op.type, block=block.idx, op_idx=op_idx)
            out = {}
        for name, spec in out.items():
            env[name] = spec
            if check:
                _check_against_decl(op, op_idx, block, name, spec, result)
        _seed_outputs_from_decl(op, block, env)
        return

    if missing:
        # an input spec is unknowable (same bail as infer_op_shapes'
        # "cannot infer yet") — not a finding, just lost coverage
        _seed_outputs_from_decl(op, block, env)
        return

    try:
        out = _eval_op(op, in_specs)
    except Exception as e:  # noqa: BLE001 — the whole point: any crash
        # inside the lowering under eval_shape means this program cannot
        # lower, reported with op provenance instead of a jnp traceback
        msg = str(e).split("\n", 1)[0][:300]
        result.add("PTV022",
                   f"lowering failed under jax.eval_shape: "
                   f"{type(e).__name__}: {msg}",
                   op_type=op.type, block=block.idx, op_idx=op_idx)
        _seed_outputs_from_decl(op, block, env)
        return

    for name, spec in out.items():
        env[name] = spec
        if check:
            _check_against_decl(op, op_idx, block, name, spec, result)
    _seed_outputs_from_decl(op, block, env)


def _check_against_decl(op, op_idx, block, name, spec, result):
    var = block._find_var_recursive(name)
    decl = declared_spec(var) if var is not None else None
    if decl is None:
        return
    shape, dtype = spec
    dshape, ddtype = decl
    if not _dims_match(shape, dshape):
        result.add("PTV020",
                   f"output {name!r}: inferred shape {list(shape)} vs "
                   f"declared {list(dshape)}",
                   op_type=op.type, block=block.idx, op_idx=op_idx,
                   var=name)
    try:
        same = _canon(dtype) == _canon(ddtype)
    except TypeError:
        same = dtype == ddtype
    if not same:
        result.add("PTV021",
                   f"output {name!r}: inferred dtype {dtype} vs "
                   f"declared {ddtype}",
                   op_type=op.type, block=block.idx, op_idx=op_idx,
                   var=name)

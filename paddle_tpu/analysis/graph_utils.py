"""Shared program-graph analyses: reachability, anchoring, alias scans.

One implementation consumed by BOTH the lint side (verifier.py: PTV012
dead ops, PTV014 write-after-write, PTV015 inplace-alias hazards) and
the rewrite side (analysis/passes/: dead-op elimination, the donation
planner) — the lint reports what the rewrite acts on, so the two must
never disagree about what is dead or hazardous.

Everything here is a pure walk over Program/Block/Operator objects: no
compilation, no device work, no mutation.
"""
from __future__ import annotations

from typing import Iterable, List, Tuple

from ..core.registry import REGISTRY

__all__ = [
    "SIDE_EFFECT_OPS", "MERGE_OPS", "CTRL_FLOW_SUB_BLOCK",
    "op_names", "attr_read_names", "op_is_anchored",
    "available_at_entry", "live_op_mask", "scan_block_hazards",
    "referenced_var_names", "sub_block_index", "sub_block_read_names",
    "program_read_names",
]

# Ops whose execution is the point (host effects), so dead-op
# reachability never flags them even when nothing reads their outputs.
SIDE_EFFECT_OPS = frozenset({
    "print", "save", "save_combine", "load", "load_combine",
    "feed", "fetch", "read", "create_custom_reader", "py_func",
    "send", "recv", "prefetch", "fetch_barrier", "send_barrier",
    "checkpoint_notify", "geo_sgd_send", "distributed_notify",
    "listen_and_serv", "fl_listen_and_serv", "delete_var",
    "push_box_sparse", "gen_nccl_id", "c_gen_nccl_id", "c_comm_init",
    "c_comm_init_all", "c_sync_calc_stream", "c_sync_comm_stream",
    # host-RPC table ops: the pull touches (and for auto-grown tables
    # mutates) pserver state, and their GRADS perform the sparse push —
    # see the grad::generic clause in op_is_anchored
    "distributed_lookup_table", "lookup_sparse_table", "pull_box_sparse",
})

# Control-flow ops that legitimately re-write a var another op already
# wrote (branch merge / carry patterns) — excluded from write-after-write.
MERGE_OPS = frozenset({
    "conditional_block", "conditional_block_infer", "while",
    "select_input", "merge_lod_tensor", "assign", "recurrent",
})

CTRL_FLOW_SUB_BLOCK = ("while", "conditional_block",
                       "conditional_block_infer", "recurrent",
                       "recompute_segment")

# Attrs through which control-flow ops read parent-scope vars by name.
_READ_ATTRS = ("input_vars", "carried_vars", "condition")


def op_names(op, which) -> List[str]:
    """Flat list of an op's input ('in') or output ('out') var names."""
    d = op.inputs if which == "in" else op.outputs
    return [n for ns in d.values() for n in ns if n]


def attr_read_names(op, attrs=_READ_ATTRS) -> set:
    """Var names an op reads through string/list attrs (control-flow
    carries, conditions) rather than input slots."""
    names = set()
    for attr in attrs:
        v = op.attrs.get(attr)
        if isinstance(v, str):
            names.add(v)
        elif isinstance(v, (list, tuple)):
            names |= {str(x) for x in v}
    return names


def sub_block_index(program, op):
    """The valid sub-block index an op carries, or None. Accepts both
    the live int form and the serialized {"__block__": idx} form."""
    sb = op.attrs.get("sub_block")
    if isinstance(sb, dict):
        sb = sb.get("__block__")
    if isinstance(sb, int) and 0 < sb < len(program.blocks):
        return sb
    return None


def sub_block_read_names(program, op) -> set:
    """Every var name read anywhere inside `op`'s sub-block — op inputs
    AND attr-based reads, transitively through nested control-flow ops
    (a conditional_block inside a while body counts).

    This is THE definition of "a sub-block read is a use", shared by
    the dead-op reachability (live_op_mask / PTV012 / DCE), the
    unused-output lint (PTV013), the donation planner, and the memory
    planner's liveness intervals, so a var whose only reader lives two
    blocks down is never declared dead by one consumer and live by
    another. The one-level scan this replaces missed nested sub-blocks
    and sub-op attr reads entirely.
    """
    names = set()
    seen = set()
    stack = [op]
    while stack:
        sb = sub_block_index(program, stack.pop())
        if sb is None or sb in seen:
            continue
        seen.add(sb)
        for sop in program.blocks[sb].ops:
            names |= set(op_names(sop, "in"))
            names |= attr_read_names(sop)
            if sop.type in CTRL_FLOW_SUB_BLOCK:
                stack.append(sop)
    return names


def op_is_anchored(op, block) -> bool:
    """Ops kept live regardless of fetch reachability: host effects,
    in-place state updates, writes to persistable vars, opless sinks."""
    if op.type in SIDE_EFFECT_OPS:
        return True
    # the grad of a host-effect op is itself a host effect (e.g. the
    # sparse PUSH inside distributed_lookup_table's grad) even when
    # nothing reads the emitted gradient tensor
    if op.type == "grad::generic" and \
            op.attrs.get("fwd_type") in SIDE_EFFECT_OPS:
        return True
    opdef = REGISTRY._ops.get(op.type)
    if opdef is not None and opdef.inplace:
        return True
    outs = op_names(op, "out")
    if not outs:
        return True
    for n in outs:
        v = block._find_var_recursive(n)
        if v is not None and v.persistable:
            return True
    return False


def available_at_entry(program, block) -> set:
    """Vars readable before any op of `block` runs: the whole ancestor
    scope chain (sub-blocks are entered mid-parent, and shapes are
    static, so the parent's full symbol table is a sound
    over-approximation) plus local persistables/data vars."""
    avail = set()
    blk = block
    while blk is not None:
        if blk is block:
            avail |= {n for n, v in blk.vars.items()
                      if v.persistable or v.is_data}
        else:
            avail |= set(blk.vars)
        blk = blk.parent
    return avail


def live_op_mask(program, fetch_list: Iterable[str]) -> List[bool]:
    """Backward reachability from the fetch targets over the global
    block: mask[i] is True iff global-block op i is anchored or some
    output transitively feeds a fetch. The False entries are exactly
    the PTV012 findings and exactly what dead-op elimination removes."""
    block = program.global_block()
    needed = set(fetch_list)
    # lengths companions are read implicitly by the feed path
    needed |= set(program.lod_link.values())
    mask = [False] * len(block.ops)
    for op_idx in reversed(range(len(block.ops))):
        op = block.ops[op_idx]
        outs = op_names(op, "out")
        live = op_is_anchored(op, block) or any(n in needed for n in outs)
        mask[op_idx] = live
        if live:
            needed |= set(op_names(op, "in"))
            # sub-block reads count: condition/carried vars resolve
            # against the parent scope too, transitively through
            # nested control flow (sub_block_read_names)
            needed |= attr_read_names(op)
            if op.type in CTRL_FLOW_SUB_BLOCK:
                needed |= sub_block_read_names(program, op)
    return mask


def scan_block_hazards(block) -> Tuple[list, list, list]:
    """One forward walk of `block` shared by the WAW/alias lints and
    the donation planner. Returns (waw, alias_reads, inplace_writes):

    - waw: (op_idx, op_type, var, prev_idx, prev_type) — `var` written
      by op prev_idx is overwritten at op_idx before anything read it
      (PTV014; persistable / inplace / merge writes are exempt).
    - alias_reads: (op_idx, op_type, var, w_idx, w_type) — `var` was
      updated in place by op w_idx but read again at op_idx, so a
      donated buffer may already be clobbered (PTV015).
    - inplace_writes: (op_idx, op_type, var) — in-place self-aliasing
      writes (optimizer state updates); minus the alias_reads vars,
      these are the safely-donatable buffers.
    """
    waw = []
    alias_reads = []
    inplace_writes = []
    last_write = {}  # var -> (op_idx, op_type, is_merge_or_inplace)
    for op_idx, op in enumerate(block.ops):
        opdef = REGISTRY._ops.get(op.type)
        ins = list(op_names(op, "in"))
        outs = list(op_names(op, "out"))

        for name in ins:
            for w_idx, w_type, w_var in inplace_writes:
                if name == w_var:
                    alias_reads.append((op_idx, op.type, name,
                                        w_idx, w_type))
            if name in last_write:
                last_write.pop(name, None)

        is_inplace = bool(opdef is not None and opdef.inplace)
        is_merge = op.type in MERGE_OPS
        for name in outs:
            var = block._find_var_recursive(name)
            persistable = bool(var is not None and var.persistable)
            prev = last_write.get(name)
            if prev is not None and not persistable \
                    and not (is_inplace or is_merge):
                p_idx, p_type, p_soft = prev
                if not p_soft:
                    waw.append((op_idx, op.type, name, p_idx, p_type))
            last_write[name] = (op_idx, op.type,
                                is_inplace or is_merge or persistable)
            if is_inplace and name in ins:
                inplace_writes.append((op_idx, op.type, name))
    return waw, alias_reads, inplace_writes


def program_read_names(program) -> set:
    """Every var name READ anywhere in the program: op inputs of every
    block plus attr-carried names (conditions, carried vars, the
    output_vars lists control-flow ops resolve by name). The complement
    of this set over an op's outputs is the PTV013 "never read"
    finding, and the memory planner's last-use scan must agree with it.
    Includes the lod_link companions the feed path reads implicitly."""
    reads = set(program.lod_link.values())
    for blk in program.blocks:
        for op in blk.ops:
            reads |= set(op_names(op, "in"))
            reads |= attr_read_names(
                op, _READ_ATTRS + ("output_vars",))
    return reads


def referenced_var_names(program) -> set:
    """Every var name any op of any block touches (inputs, outputs, or
    attr-based reads) — the working set a rewrite must not orphan;
    shrinkage of this set is the 'vars eliminated' a pass reports."""
    names = set()
    for blk in program.blocks:
        for op in blk.ops:
            names |= set(op_names(op, "in"))
            names |= set(op_names(op, "out"))
            names |= attr_read_names(
                op, _READ_ATTRS + ("output_vars",))
    return names

"""Static sharding analyzer: layout propagation + communication costs.

Reference analogue: the cross-replica weight-update sharding analysis of
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336) — decide statically how tensors split over
the mesh and what collectives reconcile the splits — applied to the
Program IR the way analysis/memory.py applied liveness analysis: with
ZERO device work, before any XLA compile.

The pass propagates the `parallel/layout.SpecLayout` annotations through
the global block op-by-op:

- elementwise ops preserve their operands' per-dim axis assignment (and
  flag operands that DISAGREE on a mesh axis — PTV060);
- matmul-family ops contract: both contraction dims sharded on the same
  axis means a partial-sum output (priced as an all-reduce, the Megatron
  row-parallel pattern); one side sharded means an implicit all-gather
  reshard (PTV061 when the bytes are large); different axes on the two
  contraction dims is PTV060;
- reshape/transpose remap the assignment dim-for-dim (merged/split dims
  that cannot carry their axis are priced as reshards);
- reductions drop axes: reducing over a sharded dim yields a partial
  result, priced as an all-reduce of the output;
- explicit collectives (`c_allreduce_*`, `c_allgather`, ...) and the
  MULTICHIP ops (`ring_attention`, `ulysses_attention`, `moe_ffn`,
  `shard_hint`) have dedicated rules;
- unknown ops fall back to "replicate the outputs + reshard any sharded
  input" and emit one PTV063 finding per op type.

Every priced collective sums into `collective_bytes_per_step` — the
predicted counterpart of the sharded bench path's measured value, and
now the ONE oracle behind `SpecLayout.collective_bytes_estimate`. Ring
conventions: all-reduce costs 2x the payload, all-gather /
reduce-scatter / all-to-all 1x. Gradient synchronisation is priced
per-parameter at the op that produces `{param}@GRAD` (2x payload /
shard count — identical arithmetic to the closed-form
`SpecLayout.gradient_sync_bytes`, which the regression tests reconcile
against). Non-divisible dims the layout silently replicated
(`SpecLayout.fallbacks`) become PTV062 findings.

Consumers: the `sharding_gate` below (Executor._resolve_step /
ServingEngine.warmup — FLAGS_sharding_verify, reject before the cache
key with zero compiles), `tools/program_lint.py --sharding --mesh`, and
bench.py's `collective_bytes_per_step` column. Docs:
docs/static_analysis.md, docs/sharding.md.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..core.dtypes import as_np_dtype
from ..monitor import STAT_ADD, STAT_SET
from .diagnostics import VerifyResult
from .shape_infer import OPAQUE_OPS, Spec, declared_spec, \
    infer_program_specs

__all__ = ["ShardingReport", "analyze_program_sharding", "sharding_gate",
           "reset_memo", "RESHARD_FINDING_MIN_BYTES"]

# PTV061 fires only when one op's implicit reshard moves at least this
# many bytes — below it the reshard is noise, not a hot-path hazard.
RESHARD_FINDING_MIN_BYTES = 1 << 20

# Caps so a malformed 1000-op program yields a readable report, not a
# thousand findings.
_MAX_FINDINGS_PER_RULE = 12

# Elementwise / activation-shaped ops: per-dim layouts pass through
# unchanged (superset of the fusion pass's set — here only the layout
# contract matters, not fusibility).
_ELEMENTWISE = frozenset({
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod", "elementwise_floordiv",
    "relu", "relu6", "gelu", "sigmoid", "tanh", "sqrt", "rsqrt",
    "square", "exp", "log", "abs", "floor", "ceil", "round", "pow",
    "scale", "cast", "clip", "dropout", "fill_any_like", "assign",
    "label_smooth", "sum", "fused_elementwise", "leaky_relu", "swish",
    "hard_swish", "hard_sigmoid", "elu", "softplus", "softsign",
    "silu", "increment", "logical_not", "logical_and", "logical_or",
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "maximum", "minimum",
})

# Ops that keep dim 0 (batch) from their principal input and replicate
# the rest: the window/channel dims are never sharded by the layout
# rules, so carrying only the batch axis is exact for them.
_DIM0_PRESERVING = frozenset({
    "conv2d", "conv2d_transpose", "depthwise_conv2d", "pool2d",
    "batch_norm", "bilinear_interp", "nearest_interp", "one_hot",
    "top_k", "accuracy", "add_position_encoding", "sequence_softmax",
    "lrn", "pad2d",
})

# Principal-input layouts pass through whole (same-rank, same meaning).
_PRESERVE_ALL = frozenset({"flash_attention", "layer_norm", "softmax"})

_MATMUL_OPS = frozenset({"mul", "matmul", "matmul_v2"})

_REDUCE_OPS = frozenset({"reduce_mean", "reduce_sum", "reduce_max",
                         "reduce_min", "reduce_prod", "mean"})

_ALLREDUCE_OPS = frozenset({"c_allreduce_sum", "c_allreduce_max",
                            "c_allreduce_min", "c_allreduce_prod",
                            "allreduce"})

# Principal input slot preference for rules that key on one input.
_PRINCIPAL_SLOTS = ("X", "Input", "Q", "Logits", "Out@GRAD")


def _principal_input(op) -> Optional[str]:
    for slot in _PRINCIPAL_SLOTS:
        names = op.inputs.get(slot) or ()
        for n in names:
            if n:
                return n
    for names in op.inputs.values():
        for n in names:
            if n:
                return n
    return None


class _Cost:
    """One priced collective."""
    __slots__ = ("kind", "axis", "bytes", "op_idx", "op_type", "note")

    def __init__(self, kind, axis, nbytes, op_idx, op_type, note=""):
        self.kind = kind
        self.axis = axis
        self.bytes = int(max(nbytes, 0))
        self.op_idx = op_idx
        self.op_type = op_type
        self.note = note

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "bytes": int(self.bytes),
             "where": f"{self.op_type}:0/{self.op_idx}"}
        if self.axis:
            d["axis"] = str(self.axis)
        if self.note:
            d["note"] = self.note
        return d


def _fmt_parts(parts) -> str:
    def one(p):
        if p is None:
            return "-"
        if isinstance(p, (tuple, list)):
            return "(" + ",".join(str(a) for a in p) + ")"
        return str(p)
    return "[" + ",".join(one(p) for p in parts) + "]"


class ShardingReport:
    """The artifact: per-op layouts + priced collectives + findings."""

    def __init__(self, program, layout):
        self.fingerprint = program.fingerprint()
        self.op_count = len(program.global_block().ops)
        self.mesh_axes = [str(a) for a in layout.mesh.axis_names]
        self.mesh_shape = [int(layout.mesh.shape[a])
                           for a in layout.mesh.axis_names]
        self.mesh_devices = int(layout.mesh.size)
        self.costs: List[_Cost] = []
        self.rows: List[dict] = []          # per-op: sharded/priced ops
        self.uncovered: List[str] = []      # op types with no rule
        self.result = VerifyResult()
        self.dynamic = False                # some bytes were lower bounds

    # -- totals ----------------------------------------------------------
    @property
    def collective_bytes_per_step(self) -> int:
        return int(sum(c.bytes for c in self.costs))

    @property
    def reshard_bytes_per_step(self) -> int:
        return int(sum(c.bytes for c in self.costs
                       if c.kind == "reshard"))

    @property
    def grad_sync_bytes(self) -> int:
        return int(sum(c.bytes for c in self.costs
                       if c.kind == "grad_sync"))

    def findings(self) -> VerifyResult:
        return self.result

    # -- serialization ---------------------------------------------------
    def to_record(self, model: Optional[str] = None) -> dict:
        top = sorted(self.costs, key=lambda c: (-c.bytes, c.op_idx))
        rec = {"kind": "sharding_report",
               "fingerprint": self.fingerprint[:12],
               "mesh_shape": list(self.mesh_shape),
               "mesh_axes": list(self.mesh_axes),
               "mesh_devices": int(self.mesh_devices),
               "ops": int(self.op_count),
               "uncovered_op_types": sorted(self.uncovered),
               "collective_bytes_per_step":
                   int(self.collective_bytes_per_step),
               "reshard_bytes_per_step":
                   int(self.reshard_bytes_per_step),
               "grad_sync_bytes": int(self.grad_sync_bytes),
               "dynamic": bool(self.dynamic),
               "collectives": [c.to_dict() for c in top[:20]],
               "counts": {"error": len(self.result.errors()),
                          "warn": len(self.result.warnings())},
               "findings": [d.to_dict()
                            for d in self.result.findings]}
        if model is not None:
            rec["model"] = model
        return rec


# ---------------------------------------------------------------------------
# the analysis
# ---------------------------------------------------------------------------

class _Analyzer:
    def __init__(self, program, layout, report,
                 reshard_threshold=RESHARD_FINDING_MIN_BYTES):
        self.program = program
        self.block = program.global_block()
        self.layout = layout
        self.report = report
        self.threshold = int(reshard_threshold)
        self.mesh_shape = {str(a): int(layout.mesh.shape[a])
                           for a in layout.mesh.axis_names}
        self.env: Dict[str, Tuple] = {}     # var name -> parts tuple
        self.specs: Dict[str, Spec] = {}
        self._rule_counts: Dict[str, int] = {}
        self._uncovered_seen = set()

    # -- small helpers ---------------------------------------------------
    def _find(self, rule, msg, op=None, op_idx=None, var=None):
        n = self._rule_counts.get(rule, 0)
        self._rule_counts[rule] = n + 1
        if n >= _MAX_FINDINGS_PER_RULE:
            return
        self.report.result.add(
            rule, msg, op_type=getattr(op, "type", None), block=0,
            op_idx=op_idx, var=var)

    def _spec(self, name) -> Optional[Spec]:
        spec = self.specs.get(name)
        if spec is None:
            var = self.block._find_var_recursive(name)
            spec = declared_spec(var) if var is not None else None
        return Spec(*spec) if spec is not None else None

    def _nbytes(self, name) -> int:
        spec = self._spec(name)
        if spec is None:
            return 0
        n, dyn = spec.nbytes(dyn_defaults=1)
        if dyn:
            self.report.dynamic = True
        return n

    def _axis_size(self, axes) -> int:
        n = 1
        for a in (axes if isinstance(axes, (tuple, list)) else (axes,)):
            if a is not None:
                n *= int(self.mesh_shape.get(str(a), 1))
        return n

    def _shard_factor(self, parts) -> int:
        n = 1
        for p in parts or ():
            if p is not None:
                n *= self._axis_size(p)
        return n

    def _parts_of(self, name, rank=None) -> tuple:
        parts = self.env.get(name)
        if parts is None:
            parts = ()
        if rank is not None:
            parts = tuple(parts)[:rank] \
                + (None,) * max(rank - len(parts), 0)
        return tuple(parts)

    def _rank_of(self, name) -> int:
        spec = self._spec(name)
        return len(spec.shape) if spec is not None else 0

    def _cost(self, kind, axis, nbytes, op_idx, op_type, note=""):
        self.report.costs.append(
            _Cost(kind, axis, nbytes, op_idx, op_type, note))

    def _reshard(self, name, parts, op, op_idx, why):
        """Price gathering `name` out of `parts` to replicated: the
        conservative reshard — full bytes minus what stays local."""
        factor = self._shard_factor(parts)
        if factor <= 1:
            return
        nbytes = self._nbytes(name)
        moved = nbytes - nbytes // factor
        axes = tuple(a for p in parts if p is not None
                     for a in (p if isinstance(p, (tuple, list))
                               else (p,)))
        self._cost("reshard", ",".join(str(a) for a in axes), moved,
                   op_idx, op.type, note=f"{name}: {why}")
        if moved >= self.threshold:
            self._find("PTV061",
                       f"implicit reshard of {name!r} "
                       f"({_fmt_parts(parts)} -> replicated, "
                       f"~{moved} bytes): {why}",
                       op=op, op_idx=op_idx, var=name)

    # -- the walk --------------------------------------------------------
    def run(self, feed_shapes=None, feed_names=()):
        program, layout = self.program, self.layout
        seed = None
        if feed_shapes:
            seed = {str(k): Spec(tuple(int(d) for d in s[0]),
                                 str(s[1]))
                    for k, s in feed_shapes.items()}
        self.specs = infer_program_specs(program, VerifyResult(),
                                         check=False, seed=seed)
        if len(layout) == 0:
            layout.add_program(program)

        # seed persistables from the layout table, feeds from feed_spec
        feed_set = {str(n) for n in (feed_names or ())}
        if not feed_set and seed:
            feed_set = set(seed)
        for name, var in self.block.vars.items():
            spec = self._spec(name)
            rank = len(spec.shape) if spec is not None else 0
            if getattr(var, "persistable", False):
                pspec = layout._table.get(name)
                if pspec is None:
                    pspec = layout.spec_for(
                        name, spec.shape if spec else (),
                        is_param=getattr(var, "is_parameter", False))
                parts = tuple(pspec)[:rank] \
                    + (None,) * max(rank - len(tuple(pspec)), 0)
                self.env[name] = parts
            elif var.is_data or name in feed_set:
                shape = spec.shape if spec is not None else ()
                if shape and int(shape[0]) > 0:
                    self.env[name] = tuple(
                        layout.feed_spec(name, shape))[:rank] \
                        + (None,) * max(rank - 1, 0)

        for op_idx, op in enumerate(self.block.ops):
            self._dispatch(op, op_idx)
            self._emit_row(op, op_idx)

        self._price_grad_sync()
        self._fallback_findings()
        return self.report

    def _emit_row(self, op, op_idx):
        outs = {}
        for names in op.outputs.values():
            for n in names:
                if n and any(p is not None
                             for p in self.env.get(n, ())):
                    outs[n] = _fmt_parts(self.env[n])
        costs_here = [c for c in self.report.costs
                      if c.op_idx == op_idx]
        if not outs and not costs_here:
            return
        self.report.rows.append(
            {"op": op.type, "where": f"{op.type}:0/{op_idx}",
             "out": outs,
             "bytes": int(sum(c.bytes for c in costs_here))})

    # -- dispatch --------------------------------------------------------
    def _dispatch(self, op, op_idx):
        t = op.type
        if t in ("feed", "fetch"):
            self._rule_passthrough(op)
        elif t in _ELEMENTWISE:
            self._rule_elementwise(op, op_idx)
        elif t in _MATMUL_OPS:
            self._rule_matmul(op, op_idx)
        elif t in _REDUCE_OPS:
            self._rule_reduce(op, op_idx)
        elif t == "softmax_with_cross_entropy":
            self._rule_softmax_xent(op, op_idx)
        elif t in _PRESERVE_ALL:
            self._rule_preserve(op, op_idx, all_dims=True)
        elif t in _DIM0_PRESERVING:
            self._rule_preserve(op, op_idx, all_dims=False)
        elif t in ("reshape2", "reshape", "squeeze2", "unsqueeze2",
                   "flatten2", "flatten_contiguous_range"):
            self._rule_reshape(op, op_idx)
        elif t in ("transpose2", "transpose"):
            self._rule_transpose(op, op_idx)
        elif t == "slice":
            self._rule_slice(op, op_idx)
        elif t == "concat":
            self._rule_concat(op, op_idx)
        elif t in ("lookup_table_v2", "lookup_table"):
            self._rule_lookup(op, op_idx)
        elif t == "shard_hint":
            self._rule_shard_hint(op, op_idx)
        elif t in _ALLREDUCE_OPS:
            self._rule_collective(op, op_idx, "all_reduce", 2.0)
        elif t == "c_allgather":
            self._rule_collective(op, op_idx, "all_gather", 1.0)
        elif t == "c_reducescatter":
            self._rule_collective(op, op_idx, "reduce_scatter", 1.0)
        elif t in ("c_broadcast", "broadcast"):
            self._rule_collective(op, op_idx, "broadcast", 1.0)
        elif t == "c_alltoall":
            self._rule_collective(op, op_idx, "all_to_all", 1.0)
        elif t == "ring_attention":
            self._rule_seq_attention(op, op_idx, kv_rotations=True)
        elif t == "ulysses_attention":
            self._rule_seq_attention(op, op_idx, kv_rotations=False)
        elif t == "moe_ffn":
            self._rule_moe(op, op_idx)
        elif t == "grad::generic":
            self._rule_grad(op, op_idx)
        elif "Param" in op.inputs and "Grad" in op.inputs:
            # optimizer family (sgd/momentum/adam/adamw/...): the
            # dp/fsdp mismatch between replicated grads and sharded
            # accumulators IS the priced ZeRO reduce-scatter/all-gather
            # decomposition (arxiv 2004.13336) — outputs keep their
            # table layouts, no extra cost, no PTV060.
            self._rule_passthrough(op)
        elif t in OPAQUE_OPS or t in ("while", "conditional_block",
                                      "recompute_segment"):
            self._rule_passthrough(op)
        else:
            self._rule_uncovered(op, op_idx)

    # -- rules -----------------------------------------------------------
    def _rule_passthrough(self, op):
        """Outputs take their already-seeded layouts (persistables keep
        the table spec; everything else stays replicated)."""

    def _set_out(self, name, parts):
        parts = tuple(parts)
        if any(p is not None for p in parts):
            self.env[name] = parts
        else:
            self.env.pop(name, None)

    def _aligned_in_parts(self, op, out_rank, axis_attr=None):
        """[(name, parts aligned to out_rank)] for every input with a
        known layout, numpy trailing broadcast (or the paddle
        elementwise `axis` attr when >= 0)."""
        out = []
        for names in op.inputs.values():
            for n in names:
                if not n:
                    continue
                parts = self.env.get(n)
                if parts is None:
                    continue
                rank = len(parts)
                if rank == out_rank:
                    out.append((n, tuple(parts)))
                elif rank < out_rank:
                    if axis_attr is not None and axis_attr >= 0:
                        lead = axis_attr
                    else:
                        lead = out_rank - rank
                    out.append((n, (None,) * lead + tuple(parts)
                                + (None,) * (out_rank - rank - lead)))
                else:
                    out.append((n, tuple(parts)[rank - out_rank:]))
        return out

    def _merge_parts(self, op, op_idx, aligned, out_rank):
        """Per-dim merge with PTV060 on disagreement."""
        merged = [None] * out_rank
        axis_dim: Dict[str, int] = {}
        for name, parts in aligned:
            for d, p in enumerate(parts):
                if p is None:
                    continue
                for a in (p if isinstance(p, (tuple, list)) else (p,)):
                    a = str(a)
                    if a in axis_dim and axis_dim[a] != d:
                        self._find(
                            "PTV060",
                            f"operands disagree on mesh axis {a!r}: "
                            f"{name!r} shards dim {d} but another "
                            f"operand shards dim {axis_dim[a]}",
                            op=op, op_idx=op_idx, var=name)
                        continue
                    axis_dim[a] = d
                if merged[d] is None:
                    merged[d] = p
                elif merged[d] != p:
                    self._find(
                        "PTV060",
                        f"operands disagree on dim {d}: "
                        f"{_fmt_parts([merged[d]])} vs "
                        f"{_fmt_parts([p])} ({name!r})",
                        op=op, op_idx=op_idx, var=name)
        return merged

    def _rule_elementwise(self, op, op_idx):
        out_names = [n for ns in op.outputs.values() for n in ns if n]
        if not out_names:
            return
        out_rank = max((self._rank_of(n) for n in out_names),
                       default=0)
        axis_attr = op.attrs.get("axis") \
            if isinstance(op.attrs.get("axis"), int) else None
        aligned = self._aligned_in_parts(op, out_rank, axis_attr)
        if not aligned:
            return
        merged = self._merge_parts(op, op_idx, aligned, out_rank)
        for n in out_names:
            r = self._rank_of(n)
            self._set_out(n, tuple(merged)[:r]
                          + (None,) * max(r - len(merged), 0))

    def _rule_preserve(self, op, op_idx, all_dims):
        src = _principal_input(op)
        if src is None:
            return
        src_parts = self.env.get(src)
        if src_parts is None:
            return
        for names in op.outputs.values():
            for n in names:
                if not n:
                    continue
                r = self._rank_of(n)
                if all_dims:
                    parts = tuple(src_parts)[:r] \
                        + (None,) * max(r - len(src_parts), 0)
                else:
                    parts = ((src_parts[0],) if src_parts else ()) \
                        + (None,) * max(r - 1, 0)
                self._set_out(n, parts)

    def _rule_matmul(self, op, op_idx):
        xn = (op.inputs.get("X") or [None])[0]
        yn = (op.inputs.get("Y") or [None])[0]
        on = next((n for ns in op.outputs.values()
                   for n in ns if n), None)
        if not xn or not yn or not on:
            return
        xs, ys = self._spec(xn), self._spec(yn)
        if xs is None or ys is None:
            return
        xr, yr = len(xs.shape), len(ys.shape)
        xp = list(self._parts_of(xn, xr))
        yp = list(self._parts_of(yn, yr))
        if op.type == "mul":
            xnc = int(op.attrs.get("x_num_col_dims", 1))
            ync = int(op.attrs.get("y_num_col_dims", 1))
            x_contract = list(range(xnc, xr))
            y_contract = list(range(0, ync))
            x_free, y_free = list(range(0, xnc)), list(range(ync, yr))
        else:
            tx = bool(op.attrs.get("transpose_X",
                                   op.attrs.get("trans_x", False)))
            ty = bool(op.attrs.get("transpose_Y",
                                   op.attrs.get("trans_y", False)))
            x_contract = [xr - 2 if tx else xr - 1]
            y_contract = [yr - 1 if ty else yr - 2]
            x_free = [d for d in range(xr) if d not in x_contract]
            y_free = [yr - 2 if ty else yr - 1]

        def axes_on(parts, dims):
            s = set()
            for d in dims:
                p = parts[d] if d < len(parts) else None
                if p is None:
                    continue
                for a in (p if isinstance(p, (tuple, list)) else (p,)):
                    s.add(str(a))
            return s

        cx, cy = axes_on(xp, x_contract), axes_on(yp, y_contract)
        out_rank = self._rank_of(on)
        out_parts = [None] * out_rank
        partial_axes = set()
        if cx and cy:
            if cx == cy:
                partial_axes = cx  # row-parallel partial sum
            else:
                self._find(
                    "PTV060",
                    f"contraction dims sharded on different axes: "
                    f"{xn!r} on {sorted(cx)}, {yn!r} on {sorted(cy)}",
                    op=op, op_idx=op_idx, var=on)
        elif cx or cy:
            # one-sided contraction sharding: gather that operand
            # (covers the fsdp weight all-gather — W's dim 0 is the
            # contraction dim)
            name, parts, dims = (xn, xp, x_contract) if cx \
                else (yn, yp, y_contract)
            masked = [parts[d] if d in dims else None
                      for d in range(len(parts))]
            self._reshard(name, masked, op, op_idx,
                          "contraction dim sharded on one side only")

        # free-dim propagation: X's free dims lead, Y's trail
        j = 0
        used_axes = set(partial_axes)
        lead = out_rank - len(y_free) - len(x_free)
        j = max(lead, 0)
        for d in x_free:
            if j >= out_rank:
                break
            p = xp[d] if d < len(xp) else None
            if p is not None:
                axes = {str(a) for a in
                        (p if isinstance(p, (tuple, list)) else (p,))}
                if axes & used_axes:
                    self._find(
                        "PTV060",
                        f"mesh axis {sorted(axes & used_axes)} would "
                        f"shard two output dims of {on!r}",
                        op=op, op_idx=op_idx, var=on)
                    p = None
                else:
                    used_axes |= axes
            out_parts[j] = p
            j += 1
        for k, d in enumerate(y_free):
            jj = out_rank - len(y_free) + k
            if jj < 0 or jj >= out_rank:
                continue
            p = yp[d] if d < len(yp) else None
            if p is not None:
                axes = {str(a) for a in
                        (p if isinstance(p, (tuple, list)) else (p,))}
                if axes & used_axes:
                    self._find(
                        "PTV060",
                        f"mesh axis {sorted(axes & used_axes)} would "
                        f"shard two output dims of {on!r}",
                        op=op, op_idx=op_idx, var=on)
                    p = None
                else:
                    used_axes |= axes
            if out_parts[jj] is None:
                out_parts[jj] = p
        self._set_out(on, out_parts)

        if partial_axes:
            payload = self._nbytes(on) // self._shard_factor(out_parts)
            self._cost("all_reduce",
                       ",".join(sorted(partial_axes)), 2 * payload,
                       op_idx, op.type,
                       note=f"{on}: partial sum over contraction")

    def _rule_reduce(self, op, op_idx):
        src = _principal_input(op)
        on = next((n for ns in op.outputs.values()
                   for n in ns if n), None)
        if src is None or on is None:
            return
        parts = self.env.get(src)
        if parts is None:
            return
        rank = len(parts)
        if op.type == "mean" or op.attrs.get("reduce_all"):
            dims = list(range(rank))
        else:
            dims = [d % rank if rank else 0
                    for d in (op.attrs.get("dim") or [0])]
        keep = bool(op.attrs.get("keep_dim", False))
        reduced_axes = set()
        out_parts = []
        for d in range(rank):
            if d in dims:
                p = parts[d]
                if p is not None:
                    for a in (p if isinstance(p, (tuple, list))
                              else (p,)):
                        reduced_axes.add(str(a))
                if keep:
                    out_parts.append(None)
            else:
                out_parts.append(parts[d])
        r = self._rank_of(on)
        self._set_out(on, tuple(out_parts)[:r]
                      + (None,) * max(r - len(out_parts), 0))
        if reduced_axes:
            payload = self._nbytes(on) // self._shard_factor(out_parts)
            self._cost("all_reduce", ",".join(sorted(reduced_axes)),
                       2 * payload, op_idx, op.type,
                       note=f"{on}: reduced over a sharded dim")

    def _rule_softmax_xent(self, op, op_idx):
        ln = (op.inputs.get("Logits") or [None])[0]
        if not ln:
            return
        parts = list(self._parts_of(ln, self._rank_of(ln)))
        vocab_axes = set()
        if parts and parts[-1] is not None:
            p = parts[-1]
            for a in (p if isinstance(p, (tuple, list)) else (p,)):
                vocab_axes.add(str(a))
        for slot, names in op.outputs.items():
            for n in names:
                if not n:
                    continue
                r = self._rank_of(n)
                if slot == "Softmax":
                    self._set_out(n, tuple(parts)[:r]
                                  + (None,) * max(r - len(parts), 0))
                else:  # Loss: class dim reduced away
                    lp = list(parts[:-1]) if parts else []
                    self._set_out(n, tuple(lp)[:r]
                                  + (None,) * max(r - len(lp), 0))
                    if vocab_axes:
                        # Megatron parallel cross-entropy: max and
                        # sum-exp all-reduce over the class axis
                        payload = self._nbytes(n)
                        self._cost("all_reduce",
                                   ",".join(sorted(vocab_axes)),
                                   2 * 2 * payload, op_idx, op.type,
                                   note=f"{n}: class dim sharded")

    def _rule_reshape(self, op, op_idx):
        src = _principal_input(op)
        on = next((n for n in (op.outputs.get("Out") or []) if n),
                  None)
        if src is None or on is None:
            return
        in_parts = self.env.get(src)
        if in_parts is None:
            return
        ispec, ospec = self._spec(src), self._spec(on)
        if ispec is None or ospec is None:
            return
        out_parts, lost = _remap_reshape(
            ispec.shape, tuple(in_parts), ospec.shape,
            lambda axes: self._axis_size(axes))
        self._set_out(on, out_parts)
        if lost:
            masked = [p if d in lost else None
                      for d, p in enumerate(in_parts)]
            self._reshard(src, masked, op, op_idx,
                          "sharded dim merged/split by reshape")

    def _rule_transpose(self, op, op_idx):
        src = _principal_input(op)
        on = next((n for n in (op.outputs.get("Out") or []) if n),
                  None)
        if src is None or on is None:
            return
        parts = self.env.get(src)
        if parts is None:
            return
        perm = op.attrs.get("axis") or op.attrs.get("perm") or []
        rank = len(parts)
        if len(perm) != rank:
            return
        self._set_out(on, tuple(parts[int(p) % rank] for p in perm))

    def _rule_slice(self, op, op_idx):
        src = _principal_input(op)
        on = next((n for ns in op.outputs.values()
                   for n in ns if n), None)
        if src is None or on is None:
            return
        parts = self.env.get(src)
        if parts is None:
            return
        axes = {int(a) for a in (op.attrs.get("axes") or [])}
        out = []
        sliced_sharded = []
        for d, p in enumerate(parts):
            if d in axes:
                if p is not None:
                    sliced_sharded.append(d)
                out.append(None)
            else:
                out.append(p)
        decrease = {int(a) for a in
                    (op.attrs.get("decrease_axis") or [])}
        out = [p for d, p in enumerate(out) if d not in decrease]
        r = self._rank_of(on)
        self._set_out(on, tuple(out)[:r]
                      + (None,) * max(r - len(out), 0))
        if sliced_sharded:
            masked = [p if d in sliced_sharded else None
                      for d, p in enumerate(parts)]
            self._reshard(src, masked, op, op_idx,
                          "slice along a sharded dim")

    def _rule_concat(self, op, op_idx):
        on = next((n for ns in op.outputs.values()
                   for n in ns if n), None)
        if on is None:
            return
        out_rank = self._rank_of(on)
        cat = int(op.attrs.get("axis", 0)) % max(out_rank, 1)
        aligned = self._aligned_in_parts(op, out_rank)
        if not aligned:
            return
        merged = self._merge_parts(op, op_idx, aligned, out_rank)
        if merged and merged[cat] is not None:
            for name, parts in aligned:
                if parts[cat] is not None:
                    masked = [p if d == cat else None
                              for d, p in enumerate(parts)]
                    self._reshard(name, masked, op, op_idx,
                                  "concat along a sharded dim")
            merged[cat] = None
        self._set_out(on, merged)

    def _rule_lookup(self, op, op_idx):
        ids = (op.inputs.get("Ids") or [None])[0]
        w = (op.inputs.get("W") or [None])[0]
        on = next((n for ns in op.outputs.values()
                   for n in ns if n), None)
        if not ids or not w or not on:
            return
        wp = list(self._parts_of(w, self._rank_of(w)))
        if wp and wp[0] is not None:
            # vocab dim sharded (fsdp): gather the table before lookup
            self._reshard(w, [wp[0]] + [None] * (len(wp) - 1), op,
                          op_idx, "embedding table row-sharded")
            wp[0] = None
        idp = self._parts_of(ids, self._rank_of(ids))
        r = self._rank_of(on)
        emb_part = wp[-1] if len(wp) >= 2 else None
        # ids often carry a trailing [.., 1] dim the lookup squeezes
        lead = list(idp)[:max(r - 1, 0)]
        parts = tuple(lead) + (None,) * max(r - 1 - len(lead), 0) \
            + (emb_part,)
        self._set_out(on, parts[:r])

    def _rule_shard_hint(self, op, op_idx):
        src = _principal_input(op)
        on = next((n for ns in op.outputs.values()
                   for n in ns if n), None)
        if on is None:
            return
        raw = op.attrs.get("spec") or []
        spec = self._spec(on) or (src and self._spec(src))
        shape = spec.shape if spec else ()
        parts = []
        for d, p in enumerate(raw):
            if p is None:
                parts.append(None)
                continue
            axes = tuple(p) if isinstance(p, (tuple, list)) else (p,)
            known = [str(a) for a in axes
                     if str(a) in self.mesh_shape]
            if len(known) != len(axes):
                parts.append(None)
                continue
            size = self._axis_size(known)
            dim = int(shape[d]) if d < len(shape) else -1
            if dim > 0 and size > 1 and dim % size != 0:
                self._find(
                    "PTV062",
                    f"shard_hint wants {on!r} dim {d} ({dim}) over "
                    f"{known} (size {size}) but it does not divide — "
                    f"silently replicated", op=op, op_idx=op_idx,
                    var=on)
                parts.append(None)
            elif size > 1:
                parts.append(known[0] if len(known) == 1
                             else tuple(known))
            else:
                parts.append(None)
        r = self._rank_of(on)
        parts = tuple(parts)[:r] + (None,) * max(r - len(parts), 0)
        if src is not None:
            in_parts = self._parts_of(src, r)
            if any(p is not None for p in in_parts) \
                    and tuple(in_parts) != tuple(parts):
                self._reshard(src, in_parts, op, op_idx,
                              "shard_hint changes the layout")
        self._set_out(on, parts)

    def _rule_collective(self, op, op_idx, kind, mult):
        src = _principal_input(op)
        on = next((n for ns in op.outputs.values()
                   for n in ns if n), None)
        if src is None:
            return
        axis = op.attrs.get("axis_name")
        nbytes = self._nbytes(src)
        self._cost(kind, axis, int(mult * nbytes), op_idx, op.type)
        if on is not None:
            parts = self.env.get(src)
            if parts is not None:
                self._set_out(on, parts)

    def _rule_seq_attention(self, op, op_idx, kv_rotations):
        qn = (op.inputs.get("Q") or [None])[0]
        on = next((n for ns in op.outputs.values()
                   for n in ns if n), None)
        axis = op.attrs.get("seq_axis")
        kv_bytes = sum(self._nbytes((op.inputs.get(s) or [""])[0])
                       for s in ("K", "V"))
        if kv_rotations:
            # ring: K/V blocks traverse the whole seq axis once
            self._cost("ring", axis, kv_bytes, op_idx, op.type,
                       note="K/V rotation around the seq axis")
        else:
            # Ulysses: all-to-all on Q/K/V in and on the output back
            q_bytes = self._nbytes(qn) if qn else 0
            out_bytes = self._nbytes(on) if on else 0
            self._cost("all_to_all", axis,
                       q_bytes + kv_bytes + out_bytes, op_idx,
                       op.type, note="head<->seq resharding")
        if qn and on is not None:
            parts = self.env.get(qn)
            if parts is not None:
                self._set_out(on, parts)

    def _rule_moe(self, op, op_idx):
        xn = (op.inputs.get("X") or [None])[0]
        axis = op.attrs.get("ep_axis")
        if xn:
            x_bytes = self._nbytes(xn)
            # dispatch + combine all-to-alls over the expert axis
            self._cost("all_to_all", axis, 2 * x_bytes, op_idx,
                       op.type, note="expert dispatch + combine")
        for names in op.outputs.values():
            for n in names:
                if n and xn:
                    parts = self.env.get(xn)
                    if parts is not None:
                        r = self._rank_of(n)
                        self._set_out(
                            n, tuple(parts)[:r]
                            + (None,) * max(r - len(parts), 0))

    def _rule_grad(self, op, op_idx):
        """grad::generic (backward.py): the grad of forward var F takes
        F's layout — gradients co-shard with what they differentiate.
        Synchronisation is priced once per parameter at the end (the
        per-param all-reduce / reduce-scatter+all-gather), not here, so
        partial-grad merges never double-count."""
        for slot, names in op.outputs.items():
            if not slot.endswith("@GRAD"):
                continue
            fwd_names = op.inputs.get(slot[:-len("@GRAD")]) or []
            for gname, fname in zip(names, fwd_names):
                if not gname or not fname:
                    continue
                base = gname.split("@RENAME@", 1)[0]
                fwd_parts = self.env.get(fname)
                if fwd_parts is None and base.endswith("@GRAD"):
                    fwd_parts = self.env.get(base[:-len("@GRAD")])
                if fwd_parts is not None:
                    r = self._rank_of(gname) or len(fwd_parts)
                    self._set_out(
                        gname, tuple(fwd_parts)[:r]
                        + (None,) * max(r - len(fwd_parts), 0))

    def _rule_uncovered(self, op, op_idx):
        """Conservative default: outputs replicate; sharded inputs are
        priced as a gather-to-replicated reshard (PTV063 once per op
        type)."""
        if op.type not in self._uncovered_seen:
            self._uncovered_seen.add(op.type)
            self.report.uncovered.append(op.type)
            self._find("PTV063",
                       f"no sharding propagation rule for "
                       f"{op.type!r}: outputs treated as replicated, "
                       f"sharded inputs priced as reshards",
                       op=op, op_idx=op_idx)
        for names in op.inputs.values():
            for n in names:
                if not n:
                    continue
                parts = self.env.get(n)
                if parts is not None \
                        and any(p is not None for p in parts):
                    self._reshard(n, parts, op, op_idx,
                                  f"input of uncovered op "
                                  f"{op.type!r}")
        for names in op.outputs.values():
            for n in names:
                if n:
                    self.env.pop(n, None)

    # -- program-level pricing -------------------------------------------
    def _price_grad_sync(self):
        """Per-parameter gradient synchronisation: 2x payload per step
        (ring all-reduce, or the equivalent reduce-scatter+all-gather
        when the update is sharded) — the same arithmetic as
        SpecLayout.gradient_sync_bytes, attributed to the op producing
        each {param}@GRAD."""
        layout = self.layout
        sync = layout.dp * (layout.fsdp
                            if getattr(layout, "fsdp_axis", None)
                            and layout.fsdp > 1 else 1)
        if sync <= 1:
            return
        last_writer: Dict[str, int] = {}
        for op_idx, op in enumerate(self.block.ops):
            for names in op.outputs.values():
                for n in names:
                    if n:
                        last_writer[n] = op_idx
        axis = layout.data_axis or getattr(layout, "fsdp_axis", None)
        for v in self.program.list_vars():
            if not getattr(v, "is_parameter", False):
                continue
            gname = f"{v.name}@GRAD"
            if gname not in last_writer:
                continue
            shape = tuple(s for s in (getattr(v, "shape", ()) or ())
                          if s and s > 0)
            if not shape:
                continue
            try:
                itemsize = np.dtype(as_np_dtype(v.dtype)).itemsize
            except Exception:
                itemsize = 4
            nbytes = int(np.prod(shape)) * itemsize
            payload = nbytes // layout.shard_count(v.name, shape)
            op_idx = last_writer[gname]
            self._cost("grad_sync", axis, 2 * payload, op_idx,
                       self.block.ops[op_idx].type,
                       note=f"{gname}: per-step gradient sync")

    def _fallback_findings(self):
        for fb in getattr(self.layout, "fallbacks", ()):
            self._find(
                "PTV062",
                f"{fb['name']!r} dim {fb['dim']} ({fb['dim_size']}) "
                f"does not divide mesh axis {fb['axis']!r} "
                f"(size {fb['axis_size']}) — silently replicated",
                var=fb["name"])


def _remap_reshape(in_shape, in_parts, out_shape, axis_size):
    """Dim-correspondence remap for reshape: returns (out_parts,
    lost_in_dims). Sharded dims carry over 1:1 matches and the leading
    dim of a merge/split group (when the axis still divides); anything
    else is lost (-> reshard)."""
    out_parts = [None] * len(out_shape)
    lost = []
    i = j = 0
    ni, nj = len(in_shape), len(out_shape)

    def dyn(d):
        return d is None or int(d) < 0

    while i < ni and j < nj:
        i0, j0 = i, j
        pi = 1 if dyn(in_shape[i]) else int(in_shape[i])
        pj = 1 if dyn(out_shape[j]) else int(out_shape[j])
        any_dyn = dyn(in_shape[i]) or dyn(out_shape[j])
        i += 1
        j += 1
        while pi != pj and not any_dyn:
            if pi < pj:
                if i >= ni:
                    break
                any_dyn = any_dyn or dyn(in_shape[i])
                pi *= 1 if dyn(in_shape[i]) else int(in_shape[i])
                i += 1
            else:
                if j >= nj:
                    break
                any_dyn = any_dyn or dyn(out_shape[j])
                pj *= 1 if dyn(out_shape[j]) else int(out_shape[j])
                j += 1
        group_in = list(range(i0, i))
        group_out = list(range(j0, j))
        if len(group_in) == 1 and len(group_out) == 1:
            out_parts[j0] = in_parts[i0] \
                if i0 < len(in_parts) else None
            continue
        # merge/split group: only the leading in-dim's axis can ride
        # along, and only onto the leading out-dim (row-major order
        # keeps the leading-axis blocks contiguous)
        for d in group_in:
            p = in_parts[d] if d < len(in_parts) else None
            if p is None:
                continue
            size = axis_size(p)
            od = group_out[0]
            out_dim = out_shape[od] if od < len(out_shape) else -1
            if d == group_in[0] and not dyn(out_dim) \
                    and int(out_dim) % max(size, 1) == 0 \
                    and out_parts[od] is None:
                out_parts[od] = p
            else:
                lost.append(d)
    # trailing unmatched in-dims with sharding are lost
    for d in range(i, ni):
        if d < len(in_parts) and in_parts[d] is not None:
            lost.append(d)
    return tuple(out_parts), lost


def analyze_program_sharding(
        program, layout, feed_names: Iterable[str] = (),
        fetch_names: Iterable[str] = (),
        feed_shapes: Optional[Dict] = None,
        reshard_threshold: int = RESHARD_FINDING_MIN_BYTES
        ) -> ShardingReport:
    """Propagate `layout` through `program`'s global block -> a
    ShardingReport (per-op layouts, priced collectives, PTV060-063
    findings). `layout` is a parallel/layout.SpecLayout over a real
    Mesh or a device-free MeshDims — no devices are needed."""
    report = ShardingReport(program, layout)
    _Analyzer(program, layout, report,
              reshard_threshold=reshard_threshold).run(
        feed_shapes=feed_shapes, feed_names=feed_names)
    return report


# ---------------------------------------------------------------------------
# the pre-compile gate (Executor._resolve_step / ServingEngine.warmup)
# ---------------------------------------------------------------------------

_MEMO_LOCK = threading.Lock()
_GATE_MEMO: "OrderedDict[tuple, ShardingReport]" = OrderedDict()
_MEMO_CAP = 64


def reset_memo():
    """Drop gate memoization (tests; after flag flips)."""
    with _MEMO_LOCK:
        _GATE_MEMO.clear()


def _mesh_dims_from_flags():
    from ..core.flags import FLAGS
    spec = str(FLAGS.sharded_mesh or "").strip()
    if not spec:
        return None
    dims = tuple(int(d) for d in spec.replace("x", ",").split(",")
                 if d.strip())
    if not dims or any(d < 1 for d in dims):
        return None
    return dims


def sharding_gate(program, layout=None, feed_shapes: Optional[Dict] = None,
                  fetch_names=None, where="executor"
                  ) -> Optional[ShardingReport]:
    """The FLAGS_sharding_verify gate: off | warn (default) | error.

    Engages only when a layout is in scope: an explicit SpecLayout (the
    sharded-exec path passes the CompiledProgram's state_spec_fn), or a
    device-free one built from FLAGS_sharded_mesh. Analyzes once per
    (fingerprint, mesh, feed shapes, fetches) and memoizes; in 'error'
    mode PTV060 layout-inconsistent findings raise
    ProgramVerificationError — callers place this BEFORE the
    executable-cache key, so a layout-broken program is rejected with
    cache_stats() showing zero compiles attempted. Everything else
    (PTV061/062/063, and all findings in 'warn' mode) surfaces as one
    summarized warning per fresh analysis.
    """
    from ..core.flags import FLAGS
    mode = FLAGS.sharding_verify
    if mode == "off":
        return None
    if mode not in ("warn", "error"):
        raise ValueError(
            f"FLAGS_sharding_verify={mode!r}: expected 'off', 'warn' "
            f"or 'error'")

    from ..parallel.layout import MeshDims, SpecLayout
    if not isinstance(layout, SpecLayout):
        layout = None
    if layout is not None:
        mesh_sig = tuple((str(a), int(layout.mesh.shape[a]))
                         for a in layout.mesh.axis_names)
    else:
        dims = _mesh_dims_from_flags()
        if dims is None:
            return None
        mesh_sig = ("flags", dims)

    shapes_sig = tuple(sorted(
        (str(n), tuple(int(d) for d in s[0]), str(s[1]))
        for n, s in (feed_shapes or {}).items()))
    key = (program.fingerprint(), mesh_sig, shapes_sig,
           tuple(str(n) for n in (fetch_names or ())))
    with _MEMO_LOCK:
        report = _GATE_MEMO.get(key)
        if report is not None:
            _GATE_MEMO.move_to_end(key)
    fresh = report is None
    if fresh:
        if layout is None:
            layout = SpecLayout(MeshDims(mesh_sig[1]))
        report = analyze_program_sharding(
            program, layout,
            feed_names=[n for n, _, _ in shapes_sig],
            fetch_names=key[3],
            feed_shapes=dict((n, (shp, dt))
                             for n, shp, dt in shapes_sig))
        with _MEMO_LOCK:
            _GATE_MEMO[key] = report
            while len(_GATE_MEMO) > _MEMO_CAP:
                _GATE_MEMO.popitem(last=False)
        STAT_ADD("analysis.shard_reports")
        STAT_SET("analysis.shard_collective_bytes",
                 report.collective_bytes_per_step)
        STAT_SET("analysis.shard_reshard_bytes",
                 report.reshard_bytes_per_step)

    res = report.result
    if mode == "error":
        if res.errors():
            STAT_ADD("analysis.shard_gate_rejects")
            res.raise_if_errors()
        if fresh and res.findings:
            _warn_once(where, res)
    elif fresh and res.findings:
        _warn_once(where, res)
    return report


def _warn_once(where, res):
    import warnings
    warnings.warn(f"[{where}] sharding analysis: {res.summary()} "
                  f"(FLAGS_sharding_verify; see docs/sharding.md)")

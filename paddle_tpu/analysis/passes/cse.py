"""Common-subexpression elimination over the global block.

Classic value numbering: each op is keyed on (op_type, canonical attr
JSON, per-slot input (name, version) tuples) where a var's version
bumps at every write — two ops with the same key compute the same
values, so the second is dropped and later reads of its outputs are
renamed to the first op's outputs. An available expression dies when
any of its outputs is overwritten (version check at lookup), and a
rename dies when its source name is redefined by a kept op.

Never merged: stateful ops (their PRNG folds in op.id — two identical
dropout ops are intentionally different), inplace/side-effect/opaque
and control-flow ops, ops writing persistable/data/fetched/lod-linked
vars, and ops whose outputs sub-blocks read by name (renaming across a
block boundary is not worth the bookkeeping).
"""
from __future__ import annotations

import json

from ...core.registry import REGISTRY
from ...framework import _jsonable_attrs
from ...monitor import STAT_ADD
from ..graph_utils import (CTRL_FLOW_SUB_BLOCK, SIDE_EFFECT_OPS,
                           attr_read_names, op_names)
from ..shape_infer import OPAQUE_OPS
from .base import Pass

__all__ = ["CommonSubexprElimination"]


class CommonSubexprElimination(Pass):
    name = "cse"
    min_level = 1

    def run(self, program, ctx):
        block = program.global_block()

        # names whose defs must stay put / must not be renamed
        protected = set(ctx.fetch_names)
        protected |= set(program.lod_link)
        protected |= set(program.lod_link.values())
        for blk in program.blocks:
            for op in blk.ops:
                protected |= attr_read_names(
                    op, ("input_vars", "carried_vars", "condition",
                         "output_vars"))
                if blk.idx != block.idx:
                    protected |= set(op_names(op, "in"))

        # A surviving expression is only a valid rename source if its
        # outputs are never redefined: a later write to the source var
        # would silently redirect renamed reads to the new value.
        write_count = {}
        for blk in program.blocks:
            for op in blk.ops:
                for n in op_names(op, "out"):
                    write_count[n] = write_count.get(n, 0) + 1

        version = {}  # name -> write count
        table = {}    # expr key -> (outputs {slot: [names]}, out versions)
        rename = {}   # dropped-def name -> surviving name
        removed = 0
        new_ops = []

        for op in block.ops:
            for slot, names in op.inputs.items():
                nn = [rename.get(n, n) for n in names]
                if nn != names:
                    op.inputs[slot] = nn

            outs = op_names(op, "out")
            opdef = REGISTRY._ops.get(op.type)
            eligible = (
                opdef is not None and not opdef.stateful
                and not opdef.inplace
                and op.type not in SIDE_EFFECT_OPS
                and op.type not in OPAQUE_OPS
                and op.type not in CTRL_FLOW_SUB_BLOCK
                and "sub_block" not in op.attrs
                and bool(outs))
            if eligible:
                for n in outs:
                    v = block._find_var_recursive(n)
                    if n in protected or (
                            v is not None and (v.persistable
                                               or v.is_data)):
                        eligible = False
                        break

            key = None
            if eligible:
                key = (op.type,
                       json.dumps(_jsonable_attrs(op.attrs),
                                  sort_keys=True),
                       tuple((slot,
                              tuple((n, version.get(n, 0))
                                    for n in names))
                             for slot, names in sorted(
                                 op.inputs.items())))
                prior = table.get(key)
                if prior is not None:
                    p_outs, p_vers = prior
                    # the available expression must be un-clobbered and
                    # slot-compatible with this op's outputs
                    valid = all(version.get(n, 0) == v
                                for n, v in p_vers.items())
                    valid = valid and all(
                        len(p_outs.get(slot, ())) == len(names)
                        for slot, names in op.outputs.items())
                    if valid:
                        for slot, names in op.outputs.items():
                            for mine, theirs in zip(names,
                                                    p_outs[slot]):
                                if mine and mine != theirs:
                                    rename[mine] = theirs
                        removed += 1
                        continue  # drop the duplicate op

            new_ops.append(op)
            for n in outs:
                version[n] = version.get(n, 0) + 1
                rename.pop(n, None)  # redefinition ends the alias
            if key is not None and all(write_count.get(n, 0) == 1
                                       for n in outs):
                table[key] = (
                    {slot: list(names)
                     for slot, names in op.outputs.items()},
                    {n: version.get(n, 0) for n in outs})

        if removed:
            block.ops = new_ops
            program._fp_cache = None
            STAT_ADD("analysis.pass_ops_deduped", removed)
        return {"deduped": removed}

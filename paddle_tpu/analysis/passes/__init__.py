"""Program-IR optimization passes ahead of lowering.

Reference analogue: BuildStrategy::Apply's ~20 graph passes
(framework/details/build_strategy.cc). Here the program IS the IR
(framework.Program), so a pass is a Python rewrite over a verified
clone, gated by FLAGS_graph_opt_level:

  0 — off: compile the program exactly as built.
  1 — default: dead-op elimination (the PTV012 walk as a rewrite),
      constant folding (registered lowerings evaluated on host), CSE
      (value numbering on (op_type, attrs, input versions)).
  2 — adds elementwise-chain fusion (consecutive chains merge into one
      fused_elementwise op replaying the originals bit-exactly, with a
      shared-jax.named_scope fallback), buffer reuse (liveness
      intervals from analysis/memory.py → disjoint same-spec
      transients renamed onto one buffer, FLAGS_buffer_reuse), and the
      inplace/donation planner (PTV015 alias analysis → per-var
      jax.jit donation of hazard-free optimizer state).

Every rewrite must preserve bit-exact observable outputs (the parity
sweep in tests/test_graph_passes.py), and the optimized program must
re-verify clean with error semantics before it replaces the original.
Pipeline runs are memoized per (fingerprint, level, feeds, fetches) —
optimize_gate — and surface as analysis.pass_* monitor stats.
Catalog + flag semantics: docs/graph_passes.md.
"""
from .base import (Pass, PassContext, PassManager, default_passes,
                   optimize_gate, optimize_program, reset_memo)
from .constant_fold import FOLDABLE_OPS, ConstantFolding
from .cse import CommonSubexprElimination
from .dce import DeadOpElimination
from .donation import DonationPlanner
from .fusion import FUSABLE_OPS, ElementwiseFusionScopes
from .reuse import BufferReuse

__all__ = [
    "Pass", "PassContext", "PassManager", "default_passes",
    "optimize_program", "optimize_gate", "reset_memo",
    "DeadOpElimination", "ConstantFolding", "CommonSubexprElimination",
    "ElementwiseFusionScopes", "BufferReuse", "DonationPlanner",
    "FOLDABLE_OPS", "FUSABLE_OPS",
]

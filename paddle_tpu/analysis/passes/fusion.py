"""Elementwise-chain fusion (level 2): merge, or at least scope.

"Operator Fusion in XLA" (arXiv 2301.13062) and FusionStitching (arXiv
1811.05213) both locate the frontend's leverage in giving the compiler
fewer, larger fusion candidates. This pass finds maximal runs of
consecutive global-block ops that are (a) elementwise/activation-shaped
and (b) dataflow-chained (each op after the first reads a value the run
produced), then splices each run into ONE `fused_elementwise` op
(ops/fused.py) whose `sub_ops` attr replays the originals in order.
Numerics are bit-identical — the fused lowering calls the exact same
registered lowerings with the exact same attrs — and every sub-op
output stays an output of the fused op, so backward's grad::generic
readers (which take chain intermediates as plain inputs) still find
them.

A run that fails the merge gates (non-JSON attrs, a stateful/inplace
registration, a sub-op that redefines one of the run's external
inputs) degrades to annotation: each op gets a shared `_fusion_group`
label, which core/lowering._op_scope turns into one jax.named_scope
prefix — one fusion candidate in the HLO op_name metadata instead of N
disjoint scopes. Merged ops carry the same label, so profiles and HLO
dumps name the chain either way. The label is a plain Python
attribute, not an op attr: it must perturb neither lowering kwargs nor
the program fingerprint.
"""
from __future__ import annotations

from ...core.registry import REGISTRY
from ...monitor import STAT_ADD
from ..graph_utils import SIDE_EFFECT_OPS, op_names
from .base import Pass

__all__ = ["ElementwiseFusionScopes", "FUSABLE_OPS"]

# Per-element compute ops whose XLA lowerings are loop-fusible
# (ops/elementwise.py binaries + ops/activations.py unaries + the
# pointwise strays from ops/math.py / tensor_ops.py).
FUSABLE_OPS = frozenset({
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod", "elementwise_floordiv",
    "minus",
    "sigmoid", "logsigmoid", "exp", "gelu", "tanh", "atan", "rsqrt",
    "abs", "ceil", "floor", "cos", "acos", "sin", "asin", "round",
    "reciprocal", "log", "square", "sqrt", "relu", "relu6", "pow",
    "softplus", "softsign", "tanh_shrink", "elu", "leaky_relu",
    "brelu", "soft_relu", "stanh", "softshrink", "hard_sigmoid",
    "hard_swish", "swish", "thresholded_relu", "erf", "sign",
    "scale", "cast", "clip",
})


def _plain_json(v):
    """True when v round-trips through json.dumps unchanged — the
    sub_ops attr must keep to_json/fingerprinting working."""
    if v is None or type(v) in (str, int, float, bool):
        return True
    if type(v) in (list, tuple):
        return all(_plain_json(x) for x in v)
    if type(v) is dict:
        return all(type(k) is str and _plain_json(x) for k, x in v.items())
    return False


def _merge_spec(g_ops):
    """inputs/outputs/attrs for one fused_elementwise op, or None when
    a gate fails and the run must fall back to scope annotation."""
    ext, produced, out_names = [], set(), []
    for op in g_ops:
        opdef = REGISTRY._ops.get(op.type)
        if opdef is None or opdef.stateful or opdef.inplace:
            return None
        if op.type in SIDE_EFFECT_OPS or "sub_block" in op.attrs:
            return None
        if not _plain_json(dict(op.attrs)):
            return None
        for n in op_names(op, "in"):
            if n not in produced and n not in ext:
                ext.append(n)
        produced |= set(op_names(op, "out"))
        out_names.extend(op_names(op, "out"))
    # a sub-op redefining one of the run's external inputs would make
    # the fused op read and write the same name — an aliasing shape the
    # hazard/donation analyses must never see from a pure op
    if set(ext) & set(out_names):
        return None
    return {
        "x_names": ext,
        "out_names": out_names,
        "sub_ops": [{"type": op.type, "attrs": dict(op.attrs),
                     "inputs": {k: list(v) for k, v in op.inputs.items()},
                     "outputs": {k: list(v) for k, v in op.outputs.items()},
                     "id": op.id} for op in g_ops],
    }


class ElementwiseFusionScopes(Pass):
    name = "fusion_scopes"
    min_level = 2

    def run(self, program, ctx):
        block = program.global_block()
        ops = block.ops
        groups = {}   # start index -> [op, ...]
        start, run, run_outs = None, [], set()

        def close():
            nonlocal start, run, run_outs
            if len(run) >= 2:
                groups[start] = list(run)
            start, run, run_outs = None, [], set()

        for i, op in enumerate(ops):
            if op.type in FUSABLE_OPS:
                outs = set(op_names(op, "out"))
                chained = not run or any(n in run_outs
                                         for n in op_names(op, "in"))
                # a redefinition inside a run would leave the fused op
                # with a duplicated output name; split instead
                if not chained or (outs & run_outs):
                    close()
                if not run:
                    start = i
                run.append(op)
                run_outs |= outs
            else:
                close()
        close()

        from ...framework import Operator
        new_ops, gid, fused_ops, merged = [], 0, 0, 0
        i, n = 0, len(ops)
        while i < n:
            g_ops = groups.get(i)
            if g_ops is None:
                new_ops.append(ops[i])
                i += 1
                continue
            label = f"ewfuse{gid}"
            gid += 1
            fused_ops += len(g_ops)
            spec = _merge_spec(g_ops)
            if spec is None:
                for op in g_ops:
                    op._fusion_group = label
                new_ops.extend(g_ops)
            else:
                fop = Operator(
                    block, "fused_elementwise",
                    inputs={"X": spec["x_names"]},
                    outputs={"Out": spec["out_names"]},
                    attrs={"sub_ops": spec["sub_ops"],
                           "x_names": spec["x_names"],
                           "out_names": spec["out_names"]})
                fop._fusion_group = label
                new_ops.append(fop)
                merged += 1
            i += len(g_ops)

        if merged:
            block.ops = new_ops
            program._fp_cache = None
        if groups:
            STAT_ADD("analysis.pass_ops_fused", fused_ops)
            STAT_ADD("analysis.pass_fusion_groups", len(groups))
        return {"groups": len(groups), "fused_ops": fused_ops,
                "merged": merged}

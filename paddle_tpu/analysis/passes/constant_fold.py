"""Constant folding: evaluate compile-time-constant ops on host.

An op folds when its type is on the closed whitelist below, every
input is already a known constant (vacuously true for seeders like
fill_constant), no output is persistable, and the registered lowering
evaluates eagerly without error. Folded chains collapse to a single
`assign_value` per still-needed var (the Operator attr protocol
serializes ndarrays, framework._jsonable_attrs), so a
fill_constant→scale→cast chain becomes one literal.

The whitelist is deliberately conservative — pure, shape-static,
per-element IEEE ops only. No reductions or matmuls (eager vs fused
accumulation order could differ), no stateful/inplace/side-effect ops,
nothing opaque to abstract eval. Bit-exact parity with the unoptimized
program is the contract (tests/test_graph_passes.py).
"""
from __future__ import annotations

import numpy as np

from ...core.registry import REGISTRY
from ...monitor import STAT_ADD
from ..graph_utils import (SIDE_EFFECT_OPS, attr_read_names, op_names)
from ..shape_infer import OPAQUE_OPS
from .base import Pass

__all__ = ["ConstantFolding", "FOLDABLE_OPS"]

FOLDABLE_OPS = frozenset({
    # seeders (no inputs)
    "fill_constant", "assign_value", "eye",
    # pure per-element math
    "scale", "cast", "clip", "sign", "abs", "square", "sqrt", "rsqrt",
    "exp", "log", "floor", "ceil", "round", "reciprocal", "relu",
    "tanh", "sigmoid",
    # shape rearrangement (pure data movement)
    "reshape", "unsqueeze", "squeeze", "transpose", "concat", "stack",
    "split", "slice", "expand",
    # binary elementwise (per-element IEEE, no accumulation)
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "minus", "assign",
})

# Folding a huge literal would bloat the program JSON (and its
# fingerprint hash) for no runtime win — XLA folds device-side anyway.
_MAX_FOLD_ELEMS = 1 << 16


def _op_foldable(op, block):
    if op.type not in FOLDABLE_OPS:
        return False
    if op.type in SIDE_EFFECT_OPS or op.type in OPAQUE_OPS:
        return False
    opdef = REGISTRY._ops.get(op.type)
    if opdef is None or opdef.stateful or opdef.inplace:
        return False
    if "sub_block" in op.attrs:
        return False
    outs = op_names(op, "out")
    if not outs:
        return False
    for n in outs:
        v = block._find_var_recursive(n)
        if v is not None and (v.persistable or v.is_data):
            return False
    return True


class ConstantFolding(Pass):
    name = "constant_fold"
    min_level = 1

    def run(self, program, ctx):
        import jax
        from ...core.lowering import LowerCtx, run_op

        block = program.global_block()
        const_env = {}   # var -> np.ndarray (value at the CURRENT def)
        folded = set()   # op indices to drop
        folded_vals = {}  # op idx -> {out var: value at THAT def}

        lctx = LowerCtx(jax.random.PRNGKey(0))
        for idx, op in enumerate(block.ops):
            ins = op_names(op, "in")
            outs = op_names(op, "out")
            ok = (_op_foldable(op, block)
                  and all(n in const_env for n in ins))
            if ok:
                try:
                    env = {n: const_env[n] for n in ins}
                    run_op(op, env, lctx)
                    vals = {}
                    for n in outs:
                        v = env.get(n)
                        if v is None:
                            raise ValueError(f"{n} not produced")
                        arr = np.asarray(v)
                        if arr.size > _MAX_FOLD_ELEMS:
                            raise ValueError("too large to embed")
                        vals[n] = arr
                except Exception:
                    ok = False
            if ok:
                const_env.update(vals)
                folded.add(idx)
                folded_vals[idx] = vals
            else:
                # this op's writes are runtime values now — any prior
                # constant binding of the same name is stale
                for n in outs:
                    const_env.pop(n, None)
        if not folded:
            return {"folded": 0, "materialized": 0}

        # constants still read by surviving ops (any block), fetched,
        # or wired as lod companions must materialize as assign_value
        needed = set(ctx.fetch_names) | set(program.lod_link.values())
        for blk in program.blocks:
            for i, op in enumerate(blk.ops):
                if blk.idx == block.idx and i in folded:
                    continue
                needed |= set(op_names(op, "in"))
                needed |= attr_read_names(op)

        from ...framework import Operator
        new_ops = []
        materialized = 0
        for idx, op in enumerate(block.ops):
            if idx not in folded:
                new_ops.append(op)
                continue
            for n in op_names(op, "out"):
                if n in needed and n in folded_vals[idx]:
                    arr = folded_vals[idx][n]
                    new_ops.append(Operator(
                        block, "assign_value", outputs={"Out": [n]},
                        attrs={"values": np.ascontiguousarray(arr),
                               "dtype": str(arr.dtype),
                               "shape": [int(s) for s in arr.shape]}))
                    materialized += 1
        block.ops = new_ops
        program._fp_cache = None
        STAT_ADD("analysis.pass_ops_folded", len(folded))
        return {"folded": len(folded), "materialized": materialized}

"""Inplace/donation planner (level 2).

Executor._compile donates the whole persistable state dict wholesale
(donate_argnums=(0,)), which forces XLA to thread EVERY state var —
including read-only tables and hazard vars — through the output alias
machinery. This pass turns the PTV015 alias scan into a per-var plan:
a persistable is donate-safe iff some op updates it in place
(optimizer state: Param/Moment in == out) and no later op reads the
aliased buffer (no PTV015 hazard) and no sub-block reads it by name.

The plan is attached to the optimized program as `_donation_plan`
(plain attribute — metadata, not IR); Executor._compile splits the jit
signature into (donated_state, pinned_state, feeds, step) with
donate_argnums=(0,), so hazard-free optimizer state reuses buffers
while everything else is pinned, and never-written pinned vars drop
out of the returned state entirely (no output copy at all).
"""
from __future__ import annotations

import numpy as np

from ...core.dtypes import as_np_dtype
from ...monitor import STAT_ADD
from ..graph_utils import (attr_read_names, op_names,
                           scan_block_hazards)
from .base import Pass

__all__ = ["DonationPlanner"]


class DonationPlanner(Pass):
    name = "donation_plan"
    min_level = 2

    def run(self, program, ctx):
        block = program.global_block()
        _, alias_reads, inplace_writes = scan_block_hazards(block)
        hazard = {v for (_, _, v, _, _) in alias_reads}
        sub_reads = set()
        for blk in program.blocks:
            if blk.idx == block.idx:
                continue
            for op in blk.ops:
                # attr-carried names (conditions, carried vars) are
                # reads too — same rule as sub_block_read_names
                sub_reads |= set(op_names(op, "in"))
                sub_reads |= attr_read_names(op)

        plan = set()
        donated_bytes = 0
        for _, _, name in inplace_writes:
            if name in plan or name in hazard or name in sub_reads:
                continue
            v = block._find_var_recursive(name)
            if v is None or not v.persistable:
                continue
            plan.add(name)
            shape = v.shape or ()
            if shape and all(isinstance(d, int) and d > 0
                             for d in shape):
                donated_bytes += (int(np.prod(shape)) *
                                  np.dtype(as_np_dtype(v.dtype)).itemsize)

        program._donation_plan = frozenset(plan)
        if plan:
            STAT_ADD("analysis.pass_donate_vars", len(plan))
            STAT_ADD("analysis.pass_donate_bytes", donated_bytes)
        return {"donated_vars": len(plan),
                "donated_bytes": donated_bytes}

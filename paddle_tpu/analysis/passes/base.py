"""Pass base class, PassManager, and the memoized optimize gate.

The pipeline rewrites a CLONE of the program (Program.fingerprint is
cached and direct op mutation does not invalidate it — cloning first is
the documented protocol, framework.Program.fingerprint), runs each pass
in order, then re-verifies the result with error semantics: only a
clean optimized program replaces the original; a rejected rewrite falls
back to the unoptimized program and counts
`analysis.pass_reverify_rejects` so a pass bug degrades to a missed
optimization, never a miscompile.

`optimize_gate` mirrors verifier.verify_gate's memoization: one
pipeline run per (program fingerprint, opt level, feeds, fetches),
shared by Executor._resolve_step and ServingEngine.warmup so a warmup
ladder optimizes once, not once per cell.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import List, Optional, Tuple

from ...monitor import STAT_ADD, STAT_OBSERVE
from ..graph_utils import referenced_var_names

__all__ = ["Pass", "PassContext", "PassManager", "default_passes",
           "optimize_program", "optimize_gate", "reset_memo"]


class PassContext:
    """Per-pipeline-run state shared by the passes."""

    def __init__(self, feed_names=(), fetch_names=(), level=1):
        self.feed_names = tuple(str(n) for n in feed_names)
        self.fetch_names = tuple(str(n) for n in fetch_names)
        self.level = int(level)


class Pass:
    """One program rewrite. Subclasses mutate `program` (already a
    private clone) in place and return a detail dict of counters for
    the report table; they must never change observable numerics —
    the bit-exact parity sweep in tests/test_graph_passes.py is the
    contract."""

    name = "pass"
    min_level = 1

    def run(self, program, ctx: PassContext) -> dict:
        raise NotImplementedError


def default_passes() -> List[Pass]:
    """The standard pipeline, in dependency order: DCE first (nothing
    downstream wastes work on dead ops), folding before CSE (folding
    creates identical assign_value ops CSE then merges), fusion after
    the simplifiers (it splices the surviving chains), buffer reuse
    after fusion (it must see — and rename inside — the final fused
    slot maps), donation last (it only annotates and must see the
    final op list)."""
    from .constant_fold import ConstantFolding
    from .cse import CommonSubexprElimination
    from .dce import DeadOpElimination
    from .donation import DonationPlanner
    from .fusion import ElementwiseFusionScopes
    from .reuse import BufferReuse
    return [DeadOpElimination(), ConstantFolding(),
            CommonSubexprElimination(), ElementwiseFusionScopes(),
            BufferReuse(), DonationPlanner()]


class PassManager:
    def __init__(self, passes: Optional[List[Pass]] = None):
        self.passes = list(passes) if passes is not None \
            else default_passes()

    def run(self, program, feed_names=(), fetch_names=(),
            level: Optional[int] = None) -> Tuple[object, dict]:
        """Optimize `program` at `level` (default FLAGS_graph_opt_level).
        Returns (program, report): the optimized clone when every pass
        ran and the result re-verified clean, else the original."""
        from ...core.flags import FLAGS
        if level is None:
            level = int(FLAGS.graph_opt_level)
        level = int(level)

        gb = program.global_block()
        ops_before = len(gb.ops)
        report = {"opt_level": level, "ops_before": ops_before,
                  "ops_after": ops_before, "vars_eliminated": 0,
                  "passes": []}
        if level <= 0 or ops_before == 0:
            return program, report

        ctx = PassContext(feed_names, fetch_names, level)
        opt = program.clone()
        vars_before = referenced_var_names(opt)

        for p in self.passes:
            if level < p.min_level:
                continue
            n0 = len(opt.global_block().ops)
            t0 = time.perf_counter()
            detail = p.run(opt, ctx) or {}
            dt = time.perf_counter() - t0
            STAT_OBSERVE("analysis.pass_seconds", dt)
            entry = {"name": p.name, "ops_before": n0,
                     "ops_after": len(opt.global_block().ops),
                     "seconds": round(dt, 6)}
            entry.update(detail)
            report["passes"].append(entry)

        # rewrites mutate op lists/attrs directly; the cached
        # fingerprint (cleared by clone) must not survive them
        opt._fp_cache = None
        report["ops_after"] = len(opt.global_block().ops)
        report["vars_eliminated"] = len(
            vars_before - referenced_var_names(opt))

        # Re-verify with error semantics before the optimized program
        # replaces the original (the FLAGS_program_verify=error
        # contract): a rewrite that broke dataflow is discarded, not
        # compiled.
        from ..verifier import verify_program
        res = verify_program(opt, feed_names=ctx.feed_names,
                             fetch_names=ctx.fetch_names)
        if res.errors():
            STAT_ADD("analysis.pass_reverify_rejects")
            import warnings
            warnings.warn(
                f"graph_opt_level={level}: optimized program failed "
                f"re-verification and was discarded — {res.summary()}")
            report["rejected"] = True
            report["ops_after"] = ops_before
            report["vars_eliminated"] = 0
            return program, report

        STAT_ADD("analysis.pass_programs_optimized")
        return opt, report


def optimize_program(program, feed_names=(), fetch_names=(),
                     level: Optional[int] = None) -> Tuple[object, dict]:
    """Unmemoized single run of the default pipeline (CLI, tests)."""
    return PassManager().run(program, feed_names, fetch_names, level)


# ---------------------------------------------------------------------------
# the memoized gate (Executor._resolve_step / ServingEngine.warmup)
# ---------------------------------------------------------------------------

_MEMO_LOCK = threading.Lock()
_OPT_MEMO: "OrderedDict[tuple, Tuple[object, dict]]" = OrderedDict()
_MEMO_CAP = 64


def reset_memo():
    """Drop gate memoization (tests; after re-registering ops)."""
    with _MEMO_LOCK:
        _OPT_MEMO.clear()


def optimize_gate(program, feed_names=None, fetch_names=None,
                  where="executor") -> Tuple[object, Optional[dict]]:
    """Optimize once per (fingerprint, level, feeds, fetches) and
    memoize the (program, report) result. Level 0 returns the program
    untouched with no memo traffic."""
    from ...core.flags import FLAGS
    level = int(FLAGS.graph_opt_level)
    if level <= 0:
        return program, None
    # FLAGS_buffer_reuse changes what level 2 produces, so it joins the
    # memo key — flipping it mid-process must not serve a stale rewrite
    key = (program.fingerprint(), level, bool(FLAGS.buffer_reuse),
           tuple(sorted(str(n) for n in (feed_names or ()))),
           tuple(str(n) for n in (fetch_names or ())))
    with _MEMO_LOCK:
        hit = _OPT_MEMO.get(key)
        if hit is not None:
            _OPT_MEMO.move_to_end(key)
    if hit is not None:
        return hit
    out = PassManager().run(program, key[3], key[4], level=level)
    with _MEMO_LOCK:
        _OPT_MEMO[key] = out
        while len(_OPT_MEMO) > _MEMO_CAP:
            _OPT_MEMO.popitem(last=False)
    return out

"""Dead-op elimination: drop global-block ops with no path to a fetch.

The liveness decision IS the PTV012 lint (graph_utils.live_op_mask):
anchored ops — host effects, inplace state updates, persistable writes,
opless sinks — always survive, as do lod_link companions, so the pass
can never remove a parameter update or a side effect. With no fetch
targets every op is formally dead; the pass declines to act rather
than empty the program.
"""
from __future__ import annotations

from ...monitor import STAT_ADD
from ..graph_utils import live_op_mask
from .base import Pass

__all__ = ["DeadOpElimination"]


class DeadOpElimination(Pass):
    name = "dead_op_elim"
    min_level = 1

    def run(self, program, ctx):
        if not ctx.fetch_names:
            return {"removed": 0}
        block = program.global_block()
        mask = live_op_mask(program, ctx.fetch_names)
        removed = mask.count(False)
        if removed:
            block.ops = [op for op, live in zip(block.ops, mask)
                         if live]
            program._fp_cache = None
            STAT_ADD("analysis.pass_ops_removed", removed)
        return {"removed": removed}

"""Buffer-reuse rewrite (level 2): alias disjoint same-spec intervals.

Reference analogue: memory_optimize_pass — the reference computes SSA
lifetimes over ir::Graph and rewrites a dead var's reader/writer to an
earlier var of identical size so buffers are reused
(BuildStrategy::Apply). Here the liveness intervals come from the
static memory planner (analysis/memory.py) and the rewrite is a pure
rename over the global block, in two flavors (memory.reuse_assignments):
a transient var whose interval starts strictly after another
same-(shape, dtype) transient's interval ends is renamed onto it
(memory_optimize-style), and a transient defined by the op that LAST
READS such a buffer becomes an in-place update `root = f(root, ...)`
(inplace_op-style) — the form that actually lowers the estimated peak,
since the def op then holds one resident buffer where two stood.

Renames alone cannot deflate a TRAINING program's peak: builders append
the whole optimizer tail after backward, so every w@GRAD stays resident
from its producer to the tail and the peak op's resident set is a stack
of genuinely-overlapping gradients. The pass therefore first SINKS each
in-place state update to just past its dependency frontier
(memory.state_update_sinks — an observationally-exact interchange), so
each gradient dies at its weight's last reader, then renames over the
shortened intervals.

This generalizes passes/donation.py, which only splits the persistable
state into donated vs pinned: donation reuses buffers ACROSS steps
(optimizer state in == out), reuse collapses them WITHIN a step
(activation temporaries). The candidate gates live in
memory.reuse_assignments and are deliberately conservative — strictly
disjoint intervals, single plain writer, no name-carrying attr or
sub-block references — so the rewrite is bit-exact by construction,
and like every pass it still rides the PassManager's re-verify
fail-open. fused_elementwise ops embed their sub-op slot maps in the
`sub_ops` attr, so the rename rewrites those too.

Gated by FLAGS_buffer_reuse (on by default at level >= 2; the sweep
driver's _reuse_on/_reuse_off A/B pair flips it).
"""
from __future__ import annotations

from ...monitor import STAT_ADD
from ..memory import (analyze_program_memory, apply_state_update_sinks,
                      peak_from_intervals, reuse_assignments)
from .base import Pass

__all__ = ["BufferReuse"]


class BufferReuse(Pass):
    name = "buffer_reuse"
    min_level = 2

    def run(self, program, ctx):
        from ...core.flags import FLAGS
        if not FLAGS.buffer_reuse:
            return {"reused_vars": 0, "bytes_saved": 0, "disabled": True}

        plan = analyze_program_memory(program,
                                      feed_names=ctx.feed_names,
                                      fetch_names=ctx.fetch_names)
        est_before = plan.peak_bytes

        # interval shortening first: sinking the optimizer tail ends
        # each w@GRAD's lifetime at its weight's last reader, which
        # both deflates the backward plateau directly AND frees those
        # buffers as rename roots for later gradients
        sunk = apply_state_update_sinks(program)
        if sunk:
            plan = analyze_program_memory(program,
                                          feed_names=ctx.feed_names,
                                          fetch_names=ctx.fetch_names)

        assignments = reuse_assignments(
            program, plan.intervals,
            set(ctx.feed_names) or {
                n for n, v in program.global_block().vars.items()
                if v.is_data},
            set(ctx.fetch_names))
        if not (assignments or sunk):
            return {"reused_vars": 0, "bytes_saved": 0, "sunk_updates": 0,
                    "est_peak_bytes": plan.peak_bytes}

        # victims always map onto roots (never onto other victims), so
        # one flat dict is the whole substitution
        rename = {victim: root for victim, root, _ in assignments}
        block = program.global_block()
        for op in block.ops:
            _rename_op(op, rename)
        program._fp_cache = None

        bytes_saved = sum(nb for _, _, nb in assignments)
        est_after = _peak_after(plan, rename)
        STAT_ADD("analysis.mem_reuse_vars", len(assignments))
        STAT_ADD("analysis.mem_reuse_bytes", bytes_saved)
        if sunk:
            STAT_ADD("analysis.mem_sunk_updates", sunk)
        return {"reused_vars": len(assignments),
                "bytes_saved": bytes_saved,
                "sunk_updates": sunk,
                "est_peak_bytes": est_after,
                "est_peak_before": est_before}


def _rename_op(op, rename):
    for slots in (op.inputs, op.outputs):
        for slot, names in slots.items():
            slots[slot] = [rename.get(n, n) for n in names]
    # fused_elementwise replays its originals from the sub_ops attr and
    # builds its local env from x_names/out_names — every embedded name
    # must follow the rename or the fused lowering reads/writes the
    # retired names (KeyError under jax.eval_shape at re-verify)
    for attr in ("x_names", "out_names"):
        names = op.attrs.get(attr)
        if isinstance(names, (list, tuple)):
            op.attrs[attr] = [rename.get(n, n) for n in names]
    sub_ops = op.attrs.get("sub_ops")
    if isinstance(sub_ops, (list, tuple)):
        for sub in sub_ops:
            for key in ("inputs", "outputs"):
                d = sub.get(key)
                if isinstance(d, dict):
                    for slot, names in d.items():
                        d[slot] = [rename.get(n, n) for n in names]


def _peak_after(plan, rename):
    """Rebuild the timeline with each victim's interval renamed onto
    its root — no re-inference, just interval arithmetic
    (memory.peak_from_intervals).

    Accounting is per SEGMENT, not the union hull: in a gap between two
    occupants nothing is resident (an eager allocator — and XLA's
    buffer assignment — frees and reuses that storage), while segments
    that touch at one op are an in-place handoff and merge into one
    run, so the handoff op counts the shared buffer ONCE where the
    pre-rewrite plan counted reader and writer separately. That makes
    est_peak_bytes <= est_peak_before by construction."""
    import dataclasses
    by_root = {}
    for name, iv in plan.intervals.items():
        by_root.setdefault(rename.get(name, name), []).append(iv)
    merged = []
    for ivs in by_root.values():
        if len(ivs) == 1:
            merged.append(ivs[0])
            continue
        segs = sorted((iv.def_idx, iv.last_use) for iv in ivs)
        runs, cur = [], list(segs[0])
        for a, b in segs[1:]:
            if a <= cur[1]:
                cur[1] = max(cur[1], b)
            else:
                runs.append(cur)
                cur = [a, b]
        runs.append(cur)
        for a, b in runs:
            merged.append(dataclasses.replace(ivs[0], def_idx=a,
                                              last_use=b))
    return peak_from_intervals(merged, plan.op_count, plan.pinned_bytes)

"""Static program verifier: shape/dtype inference + graph lints.

Reference analogue: the reference framework validates every ProgramDesc
op-by-op at build time — InferShape/InferVarType in framework/operator.cc
and op_desc.cc, plus the IR pass checks under framework/ir/. paddle_tpu
infers shapes op-by-op at append time (framework.Block.append_op ->
lowering.infer_op_shapes) but until now had no whole-program check: a
malformed program surfaced as an opaque JAX traceback deep inside
core/lowering.py, or as a wasted XLA compile in a serving warmup.

This package checks a Program with ZERO device work:

- `shape_infer`: propagate (shape, dtype) through every op via
  jax.eval_shape over the registered lowering (abstract evaluation only;
  nothing is compiled or executed), with registry-level `abstract_eval`
  rules for control-flow ops and an opaque set for host/RPC/LoD-array
  ops that cannot abstract-eval.
- `verifier`: dataflow lints (use-before-def, dead ops, write-after-
  write, inplace aliasing hazards, sub-block consistency, registry and
  version checks) + the executor/serving pre-compile gate driven by
  FLAGS_program_verify=off|warn|error.

Every diagnostic carries a stable rule ID (PTVnnn), a severity, and
provenance in the same "{op_type}:{block}/{op_idx}" format the op trace
scopes use (FLAGS_op_trace_scopes), so a verifier finding and a profiler
trace row name the same op. CLI: tools/program_lint.py. Rule catalog:
docs/static_analysis.md.
"""
from .diagnostics import (Diagnostic, ProgramVerificationError, RULES,
                          VerifyResult)
from .verifier import verify_gate, verify_program


def optimize_gate(program, feed_names=None, fetch_names=None,
                  where="executor"):
    """Memoized FLAGS_graph_opt_level pipeline (analysis/passes) —
    lazy import so `import paddle_tpu.analysis` stays cheap."""
    from .passes import optimize_gate as _gate
    return _gate(program, feed_names=feed_names,
                 fetch_names=fetch_names, where=where)


__all__ = ["Diagnostic", "VerifyResult", "ProgramVerificationError",
           "RULES", "verify_program", "verify_gate", "optimize_gate"]

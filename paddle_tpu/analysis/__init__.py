"""Static program verifier: shape/dtype inference + graph lints.

Reference analogue: the reference framework validates every ProgramDesc
op-by-op at build time — InferShape/InferVarType in framework/operator.cc
and op_desc.cc, plus the IR pass checks under framework/ir/. paddle_tpu
infers shapes op-by-op at append time (framework.Block.append_op ->
lowering.infer_op_shapes) but until now had no whole-program check: a
malformed program surfaced as an opaque JAX traceback deep inside
core/lowering.py, or as a wasted XLA compile in a serving warmup.

This package checks a Program with ZERO device work:

- `shape_infer`: propagate (shape, dtype) through every op via
  jax.eval_shape over the registered lowering (abstract evaluation only;
  nothing is compiled or executed), with registry-level `abstract_eval`
  rules for control-flow ops and an opaque set for host/RPC/LoD-array
  ops that cannot abstract-eval.
- `verifier`: dataflow lints (use-before-def, dead ops, write-after-
  write, inplace aliasing hazards, sub-block consistency, registry and
  version checks) + the executor/serving pre-compile gate driven by
  FLAGS_program_verify=off|warn|error.
- `memory`: the static memory planner — liveness intervals over the
  global block, a per-op resident-bytes timeline, a peak-HBM estimate,
  and the FLAGS_memory_gate pre-compile OOM gate (PTV050/051/052) that
  rejects over-budget programs before a single XLA compile.
- `sharding`: the static sharding analyzer — propagates SpecLayout
  annotations op-by-op, prices the implied collectives into a predicted
  collective_bytes_per_step, and drives the FLAGS_sharding_verify
  pre-compile gate (PTV060-063) plus program_lint --sharding.

Every diagnostic carries a stable rule ID (PTVnnn), a severity, and
provenance in the same "{op_type}:{block}/{op_idx}" format the op trace
scopes use (FLAGS_op_trace_scopes), so a verifier finding and a profiler
trace row name the same op. CLI: tools/program_lint.py. Rule catalog:
docs/static_analysis.md.
"""
from .diagnostics import (Diagnostic, ProgramVerificationError, RULES,
                          VerifyResult)
from .verifier import verify_gate, verify_program


def optimize_gate(program, feed_names=None, fetch_names=None,
                  where="executor"):
    """Memoized FLAGS_graph_opt_level pipeline (analysis/passes) —
    lazy import so `import paddle_tpu.analysis` stays cheap."""
    from .passes import optimize_gate as _gate
    return _gate(program, feed_names=feed_names,
                 fetch_names=fetch_names, where=where)


def memory_gate(program, feed_shapes=None, fetch_names=None,
                where="executor"):
    """Memoized FLAGS_memory_gate static-memory gate (analysis/memory)
    — lazy import, same reason as optimize_gate."""
    from .memory import memory_gate as _gate
    return _gate(program, feed_shapes=feed_shapes,
                 fetch_names=fetch_names, where=where)


def sharding_gate(program, layout=None, feed_shapes=None,
                  fetch_names=None, where="executor"):
    """Memoized FLAGS_sharding_verify static-sharding gate
    (analysis/sharding) — lazy import, same reason as optimize_gate."""
    from .sharding import sharding_gate as _gate
    return _gate(program, layout=layout, feed_shapes=feed_shapes,
                 fetch_names=fetch_names, where=where)


def analyze_program_sharding(program, layout, feed_names=(),
                             fetch_names=(), feed_shapes=None):
    """Unmemoized sharding analysis -> ShardingReport (CLI, tests)."""
    from .sharding import analyze_program_sharding as _analyze
    return _analyze(program, layout, feed_names=feed_names,
                    fetch_names=fetch_names, feed_shapes=feed_shapes)


def analyze_program_memory(program, feed_names=(), fetch_names=(),
                           feed_shapes=None, budget_bytes=0):
    """Unmemoized memory analysis -> MemoryPlan (CLI, bench, tests)."""
    from .memory import analyze_program_memory as _analyze
    return _analyze(program, feed_names=feed_names,
                    fetch_names=fetch_names, feed_shapes=feed_shapes,
                    budget_bytes=budget_bytes)


__all__ = ["Diagnostic", "VerifyResult", "ProgramVerificationError",
           "RULES", "verify_program", "verify_gate", "optimize_gate",
           "memory_gate", "analyze_program_memory", "sharding_gate",
           "analyze_program_sharding"]

"""Static memory planner: liveness intervals + peak-HBM estimation.

Reference analogue: the reference framework's memory_optimize_pass /
inplace_op_pass pair computes per-var lifetimes over the SSA graph and
reuses dead buffers so models fit the device (BuildStrategy::Apply,
SURVEY §1). Here the two halves already existed — shape_infer.py infers
a (shape, dtype) Spec for every var and core/memory.py reads measured
PJRT HBM stats — and this module connects them: a def/last-use interval
per var over the global block, a per-op resident-bytes timeline, and a
peak estimate, all with ZERO device work, so the first signal that a
program does not fit is a PTV050 diagnostic before any XLA compile
instead of an OOM after one.

The liveness model (docs/memory_planning.md):

- Persistables, fed vars, fetch targets, and lod_link companions are
  PINNED: resident for the whole program (XLA threads them through the
  executable's I/O).
- Every other var referenced by a global-block op is TRANSIENT: live
  from its first writer to its last reader. A read anywhere inside a
  control-flow op's sub-blocks — transitively, including attr-carried
  names — counts as a use AT that control-flow op's index
  (graph_utils.sub_block_read_names, the same rule PTV012/PTV013 and
  DCE apply).
- Vars declared only inside sub-blocks are charged to their
  control-flow op's single index (the while body's temporaries exist
  while the loop runs).
- Sizes come from shape_infer specs; dynamic (-1/_DYN_DIM) dims
  resolve from the concrete feed shapes when the caller supplies them
  (the gate path seeds infer_program_specs) and otherwise fall back to
  Spec.nbytes' documented lower bound with a `dynamic` marker PTV050
  reports instead of guessing.

Consumers: the memory_gate below (Executor._resolve_step /
ServingEngine.warmup — reject before the cache key, zero compiles),
analysis/passes/reuse.py (the rewrite that aliases non-overlapping
same-spec intervals), tools/program_lint.py --memory, and bench.py's
est_peak_bytes calibration column.
"""
from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

from ..monitor import STAT_ADD, STAT_SET
from .diagnostics import VerifyResult
from .graph_utils import (CTRL_FLOW_SUB_BLOCK, attr_read_names, op_names,
                          sub_block_index, sub_block_read_names)
from .shape_infer import Spec, declared_spec, infer_program_specs

__all__ = ["VarInterval", "MemoryPlan", "analyze_program_memory",
           "reuse_assignments", "peak_from_intervals",
           "state_update_sinks", "apply_state_update_sinks",
           "resolve_budget_bytes", "memory_gate", "reset_memo"]

# Attrs through which ops read parent-scope vars by name (superset of
# graph_utils._READ_ATTRS: output_vars is a write-by-name, but a var
# named there must never be renamed/retimed either).
_NAME_ATTRS = ("input_vars", "carried_vars", "condition", "output_vars")

# PTV052 fires only when the estimated reuse savings are worth acting
# on: at least 1 MiB AND at least 5% of the estimated peak.
_REUSE_FINDING_MIN_BYTES = 1 << 20
_REUSE_FINDING_MIN_FRAC = 0.05


@dataclasses.dataclass
class VarInterval:
    """One var's footprint: [def_idx, last_use] over global-block op
    indices. Pinned vars span the whole program (def_idx -1). A
    dynamic=True nbytes is a lower bound (Spec.nbytes)."""
    name: str
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int
    def_idx: int
    last_use: int
    pinned: bool = False
    dynamic: bool = False

    def overlaps(self, other: "VarInterval") -> bool:
        return not (self.last_use < other.def_idx
                    or other.last_use < self.def_idx)

    def to_dict(self) -> dict:
        return {"name": self.name, "nbytes": int(self.nbytes),
                "def": int(self.def_idx), "last_use": int(self.last_use),
                "pinned": bool(self.pinned),
                "dynamic": bool(self.dynamic)}


class MemoryPlan:
    """The artifact: intervals + timeline + peak, JSONL-serializable."""

    def __init__(self, program, intervals: Dict[str, VarInterval],
                 timeline: List[int], pinned_bytes: int,
                 unsized_vars: int, budget_bytes: int = 0,
                 reuse_bytes_available: int = 0):
        self.fingerprint = program.fingerprint()
        block = program.global_block()
        self.op_count = len(block.ops)
        self.intervals = intervals
        self.timeline = timeline
        self.pinned_bytes = int(pinned_bytes)
        self.unsized_vars = int(unsized_vars)
        self.budget_bytes = int(budget_bytes)
        self.reuse_bytes_available = int(reuse_bytes_available)
        if timeline:
            self.peak_bytes = max(timeline)
            self.peak_op_idx = timeline.index(self.peak_bytes)
            op = block.ops[self.peak_op_idx]
            self.peak_op = f"{op.type}:0/{self.peak_op_idx}"
        else:
            self.peak_bytes = self.pinned_bytes
            self.peak_op_idx = -1
            self.peak_op = "program"
        self.dynamic = any(iv.dynamic for iv in intervals.values())

    # -- queries ---------------------------------------------------------
    def residents_at(self, op_idx: int) -> List[VarInterval]:
        return [iv for iv in self.intervals.values()
                if iv.def_idx <= op_idx <= iv.last_use]

    def top_residents(self, k: int = 10,
                      at: Optional[int] = None) -> List[VarInterval]:
        """The k largest vars resident at `at` (default: the peak op)."""
        at = self.peak_op_idx if at is None else at
        live = self.residents_at(at) if at >= 0 \
            else list(self.intervals.values())
        return sorted(live, key=lambda iv: (-iv.nbytes, iv.name))[:k]

    def kv_summary(self) -> Optional[dict]:
        """Decode KV-cache footprint, when this program holds one.

        Recognizes the two generation KV layouts by persistable naming
        convention: `*.kv_pool_k` / `*.kv_pool_v` are the block pools
        of the paged decode step (models/gpt.build_paged_decode_step —
        sized num_blocks x block_size, decoupled from max_slots x
        max_seq), `*.cache_k` / `*.cache_v` are the contiguous slabs of
        the classic step (sized max_slots x max_seq). Both are pinned
        at full size by the planner, so `kv_bytes` is exactly what the
        PTV050 budget gate prices them at. None when the program holds
        neither (i.e. it is not a decode program)."""
        paged = [iv for iv in self.intervals.values()
                 if iv.name.endswith((".kv_pool_k", ".kv_pool_v"))]
        slab = [iv for iv in self.intervals.values()
                if iv.name.endswith((".cache_k", ".cache_v"))]
        if not paged and not slab:
            return None
        group = paged or slab
        return {"layout": "paged" if paged else "slab",
                "kv_bytes": int(sum(iv.nbytes for iv in group)),
                "kv_vars": len(group),
                "kv_frac_of_peak": round(
                    sum(iv.nbytes for iv in group)
                    / max(self.peak_bytes, 1), 4)}

    # -- diagnostics -----------------------------------------------------
    def findings(self) -> VerifyResult:
        """PTV05x findings against `budget_bytes` (0 = no budget: only
        the budget-free PTV052 reuse advisory can fire)."""
        res = VerifyResult()
        budget = self.budget_bytes
        bound = " (lower bound: unresolved dynamic dims sized at 1)" \
            if self.dynamic else ""
        if budget > 0 and self.peak_bytes > budget:
            res.add("PTV050",
                    f"estimated peak {_fmt_bytes(self.peak_bytes)}"
                    f"{bound} exceeds the "
                    f"{_fmt_bytes(budget)} budget "
                    f"(FLAGS_memory_budget_bytes) at op {self.peak_op}; "
                    f"top residents: " + ", ".join(
                        f"{iv.name}={_fmt_bytes(iv.nbytes)}"
                        for iv in self.top_residents(3)),
                    op_type=None if self.peak_op_idx < 0 else
                    self.peak_op.split(":", 1)[0],
                    block=0, op_idx=max(self.peak_op_idx, 0))
        if budget > 0:
            over = [iv for iv in self.intervals.values()
                    if iv.nbytes > budget]
            for iv in sorted(over, key=lambda iv: -iv.nbytes)[:5]:
                res.add("PTV051",
                        f"tensor {iv.name!r} alone is "
                        f"{_fmt_bytes(iv.nbytes)}"
                        f"{' (lower bound)' if iv.dynamic else ''}, "
                        f"larger than the {_fmt_bytes(budget)} budget — "
                        f"no buffer plan can fit it", var=iv.name)
        save = self.reuse_bytes_available
        if save >= _REUSE_FINDING_MIN_BYTES and \
                save >= _REUSE_FINDING_MIN_FRAC * max(self.peak_bytes, 1):
            res.add("PTV052",
                    f"{_fmt_bytes(save)} of dead-buffer reuse is "
                    f"available (same-spec non-overlapping intervals) — "
                    f"FLAGS_graph_opt_level>=2 with FLAGS_buffer_reuse "
                    f"rewrites them onto shared buffers")
        return res

    # -- serialization ---------------------------------------------------
    def to_record(self, model: Optional[str] = None) -> dict:
        rec = {"kind": "memory_plan",
               "fingerprint": self.fingerprint[:12],
               "ops": self.op_count,
               "vars": len(self.intervals),
               "est_peak_bytes": int(self.peak_bytes),
               "pinned_bytes": int(self.pinned_bytes),
               "peak_op": self.peak_op,
               "peak_op_idx": int(self.peak_op_idx),
               "dynamic": bool(self.dynamic),
               "unsized_vars": int(self.unsized_vars),
               "budget_bytes": int(self.budget_bytes),
               "reuse_bytes_available": int(self.reuse_bytes_available),
               "top_residents": [iv.to_dict()
                                 for iv in self.top_residents(10)],
               "findings": [d.to_dict()
                            for d in self.findings().findings]}
        kv = self.kv_summary()
        if kv is not None:
            rec["kv"] = kv
        if model is not None:
            rec["model"] = model
        return rec


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


# ---------------------------------------------------------------------------
# the analysis
# ---------------------------------------------------------------------------

def _spec_of(name, env, block) -> Optional[Spec]:
    spec = env.get(name)
    if spec is None:
        var = block._find_var_recursive(name)
        spec = declared_spec(var) if var is not None else None
    return Spec(*spec) if spec is not None else None


def analyze_program_memory(program, feed_names: Iterable[str] = (),
                           fetch_names: Iterable[str] = (),
                           feed_shapes: Optional[Dict] = None,
                           budget_bytes: int = 0) -> MemoryPlan:
    """Liveness + timeline + peak for `program`'s global block.

    feed_shapes: {name: (shape, dtype)} of the concrete feed arrays —
    seeded into shape inference so dynamic dims resolve before size
    arithmetic; without it dynamic vars carry the Spec.nbytes lower
    bound and the plan is marked dynamic. feed_names defaults to
    feed_shapes' keys, else the program's is_data vars.
    """
    block = program.global_block()
    n = len(block.ops)

    if feed_shapes:
        seed = {str(k): Spec(tuple(int(d) for d in s[0]), str(s[1]))
                for k, s in feed_shapes.items()}
    else:
        seed = None
    env = infer_program_specs(program, VerifyResult(), check=False,
                              seed=seed)

    feed_set = {str(x) for x in (feed_names or ())}
    if not feed_set and seed:
        feed_set = set(seed)
    if not feed_set:
        feed_set = {name for name, v in block.vars.items() if v.is_data}
    fetch_set = {str(x) for x in (fetch_names or ())}
    # lengths companions ride along with every ragged feed
    pin_names = set(feed_set) | fetch_set | set(program.lod_link.values())
    for name, v in block.vars.items():
        if v.persistable:
            pin_names.add(name)

    # -- def / last-use over the global block ---------------------------
    first_def: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    sub_local: Dict[str, VarInterval] = {}
    for op_idx, op in enumerate(block.ops):
        reads = set(op_names(op, "in")) | attr_read_names(op)
        if op.type in CTRL_FLOW_SUB_BLOCK:
            reads |= sub_block_read_names(program, op)
            _collect_sub_locals(program, op, op_idx, env, sub_local)
        for name in reads:
            last_use[name] = op_idx
        for name in op_names(op, "out"):
            first_def.setdefault(name, op_idx)
            last_use.setdefault(name, op_idx)

    intervals: Dict[str, VarInterval] = {}
    pinned_bytes = 0
    unsized = 0
    touched = set(first_def) | set(last_use) | pin_names
    for name in sorted(touched):
        spec = _spec_of(name, env, block)
        if spec is None:
            # no declared or inferred spec (opaque host-side values,
            # TensorArrays): lost coverage, surfaced as unsized_vars
            unsized += 1
            continue
        nbytes, dynamic = spec.nbytes(dyn_defaults=1)
        pinned = name in pin_names
        iv = VarInterval(
            name=name, shape=tuple(spec.shape), dtype=str(spec.dtype),
            nbytes=nbytes, pinned=pinned, dynamic=dynamic,
            def_idx=-1 if pinned else first_def.get(
                name, last_use.get(name, 0)),
            last_use=max(n - 1, 0) if pinned
            else last_use.get(name, first_def.get(name, 0)))
        intervals[name] = iv
        if pinned:
            pinned_bytes += nbytes
    intervals.update(sub_local)

    timeline = _timeline(intervals.values(), n, pinned_bytes)
    reuse_avail = sum(nb for _, _, nb in reuse_assignments(
        program, intervals, feed_set, fetch_set))
    plan = MemoryPlan(program, intervals, timeline, pinned_bytes,
                      unsized, budget_bytes=budget_bytes,
                      reuse_bytes_available=reuse_avail)
    return plan


def _collect_sub_locals(program, op, op_idx, env, out):
    """Vars declared only inside `op`'s sub-blocks: charged to the
    control-flow op's single index (keyed name@bN to avoid colliding
    with a same-named global var)."""
    stack = [op]
    seen = set()
    while stack:
        sb = sub_block_index(program, stack.pop())
        if sb is None or sb in seen:
            continue
        seen.add(sb)
        blk = program.blocks[sb]
        for name, var in blk.vars.items():
            spec = env.get(name) or declared_spec(var)
            if spec is None:
                continue
            nbytes, dynamic = Spec(*spec).nbytes(dyn_defaults=1)
            out[f"{name}@b{sb}"] = VarInterval(
                name=f"{name}@b{sb}", shape=tuple(spec[0]),
                dtype=str(spec[1]), nbytes=nbytes, def_idx=op_idx,
                last_use=op_idx, dynamic=dynamic)
        for sop in blk.ops:
            if sop.type in CTRL_FLOW_SUB_BLOCK:
                stack.append(sop)


def _timeline(intervals, n_ops, pinned_bytes) -> List[int]:
    alloc = [0] * (n_ops + 1)
    free = [0] * (n_ops + 1)
    for iv in intervals:
        if iv.pinned:
            continue
        alloc[max(iv.def_idx, 0)] += iv.nbytes
        free[max(iv.last_use, 0)] += iv.nbytes
    timeline = []
    cur = pinned_bytes
    for i in range(n_ops):
        cur += alloc[i]
        timeline.append(cur)
        cur -= free[i]
    return timeline


def peak_from_intervals(intervals, n_ops, pinned_bytes) -> int:
    """Peak of a rebuilt timeline — the reuse pass's cheap 'what would
    the peak be after merging these intervals' query (no re-inference)."""
    tl = _timeline(intervals, n_ops, pinned_bytes)
    return max(tl) if tl else pinned_bytes


# ---------------------------------------------------------------------------
# reuse planning (consumed by analysis/passes/reuse.py and PTV052)
# ---------------------------------------------------------------------------

def reuse_assignments(program, intervals: Dict[str, VarInterval],
                      feed_set, fetch_set) -> List[Tuple[str, str, int]]:
    """Greedy linear-scan packing of same-(shape, dtype) transient
    intervals onto shared buffers -> [(victim, root, nbytes)]: rename
    `victim` to `root` and the allocation disappears.

    A var is a candidate iff renaming it can never change observable
    values or break name resolution: transient (not pinned), written
    exactly once in the global block by a plain op (no inplace/merge/
    control-flow/side-effect writers), read at least once there, and
    never referenced by name anywhere else — not in any sub-block, not
    through name-carrying attrs, not in lod_link.

    Two interval relationships qualify, mirroring the reference's
    memory_optimize_pass / inplace_op_pass split:

    - DISJOINT (the buffer's last read is strictly before the reuser's
      def op): a pure rename — each reader still receives exactly the
      value its renamed writer produced.
    - IN-PLACE (the buffer's last read IS the reuser's def op, and that
      op reads the buffer): the rename yields `root = f(root, ...)`.
      run_op gathers every input before any output is bound, so the
      dying input value is fully consumed first and the result is still
      bit-exact — but it is only the in-place form that can LOWER the
      estimated peak, because at the def op one buffer now stands where
      two were resident. fused_elementwise def ops are excluded here:
      their lowering replays sub-ops against a mutable env, so a later
      sub-op could re-read the clobbered external input.

    Either way the PTV014/PTV015 lints stay silent on the result: the
    WAW scan pops a var on read before the re-write lands, and PTV015
    only tracks registry-inplace ops.

    The pool key is the SYMBOLIC (shape, dtype): dynamic dims pair only
    with identically-placed dynamic dims, so re-verification's PTV020
    declared-vs-inferred check stays clean, and the one batch/seq axis
    a program resolves at feed time resolves identically for both.
    """
    from ..core.registry import REGISTRY
    from .graph_utils import MERGE_OPS, SIDE_EFFECT_OPS
    from .shape_infer import OPAQUE_OPS

    block = program.global_block()
    banned = set(feed_set) | set(fetch_set)
    banned |= set(program.lod_link) | set(program.lod_link.values())
    writers: Dict[str, List[int]] = {}
    for op_idx, op in enumerate(block.ops):
        banned |= attr_read_names(op, _NAME_ATTRS)
        for name in op_names(op, "out"):
            writers.setdefault(name, []).append(op_idx)
        if op.type in CTRL_FLOW_SUB_BLOCK:
            banned |= sub_block_read_names(program, op)
    for blk in program.blocks:
        if blk.idx == block.idx:
            continue
        for op in blk.ops:
            banned |= set(op_names(op, "in"))
            banned |= set(op_names(op, "out"))
            banned |= attr_read_names(op, _NAME_ATTRS)

    def plain_writer(op_idx) -> bool:
        op = block.ops[op_idx]
        if op.type in SIDE_EFFECT_OPS or op.type in OPAQUE_OPS \
                or op.type in MERGE_OPS \
                or op.type in CTRL_FLOW_SUB_BLOCK:
            return False
        opdef = REGISTRY._ops.get(op.type)
        if opdef is None or opdef.inplace:
            return False
        # writers re-reading one of their own outputs are inplace-ish
        return not (set(op_names(op, "in")) & set(op_names(op, "out")))

    cands = []
    for iv in intervals.values():
        if iv.pinned or iv.nbytes <= 0 or iv.name in banned:
            continue
        w = writers.get(iv.name, [])
        if len(w) != 1 or not plain_writer(w[0]):
            continue
        if iv.last_use <= iv.def_idx:
            # never read after its def: a root no reader ever pops
            # would trip the WAW lint on the rewritten program
            continue
        cands.append(iv)

    rename: Dict[str, str] = {}

    def inplace_ok(iv, root) -> bool:
        # equality case: the slot's last read is AT iv's def op — legal
        # only if that op actually consumes the buffer (reads root, or
        # a victim already renamed onto it) and replays nothing from a
        # mutable env (no fused_elementwise)
        op = block.ops[iv.def_idx]
        if op.type == "fused_elementwise":
            return False
        return any(rename.get(n, n) == root
                   for n in op_names(op, "in"))

    cands.sort(key=lambda iv: (iv.def_idx, iv.name))
    pool: Dict[tuple, List[list]] = {}
    out: List[Tuple[str, str, int]] = []
    for iv in cands:
        key = (iv.shape, iv.dtype)
        slots = pool.setdefault(key, [])
        # prefer the in-place form: only a handoff AT the def op
        # collapses two resident buffers into one and lowers the peak
        chosen = next((s for s in slots
                       if s[0] == iv.def_idx and inplace_ok(iv, s[1])),
                      None)
        if chosen is None:
            chosen = next((s for s in slots if s[0] < iv.def_idx), None)
        if chosen is not None:
            out.append((iv.name, chosen[1], iv.nbytes))
            rename[iv.name] = chosen[1]
            chosen[0] = iv.last_use
        else:
            slots.append([iv.last_use, iv.name])
    return out


def state_update_sinks(program) -> Dict[int, int]:
    """Plan {op_idx: target_idx} moves that sink each in-place state
    update (adamw/sgd/momentum/... — registry-inplace ops whose every
    output is a persistable) from the optimizer tail up to just past
    its dependency frontier.

    Why this lives in the memory planner: builders append ALL optimizer
    ops after the whole backward, so every weight gradient stays
    resident from its producer until the tail — on the bench builders
    that stack of w@GRAD buffers IS the peak op's resident set, and no
    rename can shrink it (the intervals genuinely overlap). Moving each
    update to the earliest legal index ends the gradient's interval at
    the point the weight was last read, deflating the plateau.

    The interchange is observationally exact under the executor's
    env-dict semantics iff nothing between target and origin (a) writes
    any of the op's inputs, (b) reads any of its outputs (they would
    see the updated value), or (c) writes any of its outputs. The
    frontier below is the last such index; reads include attr-carried
    names and transitive sub-block reads, the same rule liveness uses.
    Every op before the origin is scanned, so a mover can never hop
    over its gradient producer, a stale-weight reader, or another
    mover it depends on.
    """
    from ..core.registry import REGISTRY
    from .graph_utils import SIDE_EFFECT_OPS

    block = program.global_block()
    ops = block.ops
    reads_at, writes_at = [], []
    for op in ops:
        r = set(op_names(op, "in")) | attr_read_names(op)
        if op.type in CTRL_FLOW_SUB_BLOCK:
            r |= sub_block_read_names(program, op)
        reads_at.append(r)
        writes_at.append(set(op_names(op, "out")))

    moves: Dict[int, int] = {}
    for i, op in enumerate(ops):
        opdef = REGISTRY._ops.get(op.type)
        if opdef is None or not opdef.inplace \
                or op.type in SIDE_EFFECT_OPS \
                or op.type in CTRL_FLOW_SUB_BLOCK:
            continue
        outs = writes_at[i]
        if not outs:
            continue
        var_of = {nm: block._find_var_recursive(nm) for nm in outs}
        if any(v is None or not v.persistable for v in var_of.values()):
            continue
        ins = reads_at[i]
        frontier = -1
        for j in range(i):
            if writes_at[j] & ins or reads_at[j] & outs \
                    or writes_at[j] & outs:
                frontier = j
        if frontier + 1 < i:
            moves[i] = frontier + 1
    return moves


def apply_state_update_sinks(program,
                             moves: Optional[Dict[int, int]] = None) -> int:
    """Reorder the global block per `moves` (default: plan them).
    Movers land just before the op currently at their target index;
    relative order among ops with equal keys is preserved (stable
    sort), which keeps mover-vs-mover dependencies legal — a mover
    reading another's output has a frontier at or past that mover's
    origin. Returns the number of ops moved."""
    if moves is None:
        moves = state_update_sinks(program)
    if not moves:
        return 0
    block = program.global_block()
    keyed = sorted(enumerate(block.ops),
                   key=lambda t: (moves.get(t[0], t[0]) - 0.5
                                  if t[0] in moves else t[0], t[0]))
    block.ops = [op for _, op in keyed]
    program._fp_cache = None
    return len(moves)


# ---------------------------------------------------------------------------
# the pre-compile OOM gate (Executor._resolve_step / ServingEngine.warmup)
# ---------------------------------------------------------------------------

_MEMO_LOCK = threading.Lock()
_GATE_MEMO: "OrderedDict[tuple, MemoryPlan]" = OrderedDict()
_MEMO_CAP = 128


def reset_memo():
    """Drop gate memoization (tests; after flag flips)."""
    with _MEMO_LOCK:
        _GATE_MEMO.clear()


def resolve_budget_bytes() -> int:
    """FLAGS_memory_budget_bytes resolved: >0 = explicit budget; 0 =
    auto from the device's reported bytes_limit (0 when the backend
    reports none, e.g. CPU — the gate then cannot fire); -1 = never
    apply a budget."""
    from ..core.flags import FLAGS
    b = int(FLAGS.memory_budget_bytes)
    if b > 0:
        return b
    if b < 0:
        return 0
    from ..core.memory import device_memory_stats
    return int(device_memory_stats().get("bytes_limit", 0) or 0)


def memory_gate(program, feed_shapes: Optional[Dict] = None,
                fetch_names=None, where="executor"
                ) -> Optional[MemoryPlan]:
    """The FLAGS_memory_gate gate: off | warn | error (default error).

    Analyzes once per (program fingerprint, concrete feed shapes,
    fetch names, resolved budget) and memoizes. In 'error' mode PTV050/
    PTV051 raise ProgramVerificationError — callers place this BEFORE
    the executable-cache key, so a program that cannot fit is rejected
    with cache_stats() showing zero compiles attempted. PTV052 (and
    everything in 'warn' mode) surfaces as one summarized warning.
    """
    from ..core.flags import FLAGS
    mode = FLAGS.memory_gate
    if mode == "off":
        return None
    if mode not in ("warn", "error"):
        raise ValueError(
            f"FLAGS_memory_gate={mode!r}: expected 'off', 'warn' or "
            f"'error'")

    budget = resolve_budget_bytes()
    shapes_sig = tuple(sorted(
        (str(n), tuple(int(d) for d in s[0]), str(s[1]))
        for n, s in (feed_shapes or {}).items()))
    key = (program.fingerprint(), shapes_sig,
           tuple(str(n) for n in (fetch_names or ())), budget)
    with _MEMO_LOCK:
        plan = _GATE_MEMO.get(key)
        if plan is not None:
            _GATE_MEMO.move_to_end(key)
    fresh = plan is None
    if fresh:
        plan = analyze_program_memory(
            program, feed_names=[n for n, _, _ in shapes_sig],
            fetch_names=key[2], feed_shapes=dict(
                (n, (shp, dt)) for n, shp, dt in shapes_sig),
            budget_bytes=budget)
        with _MEMO_LOCK:
            _GATE_MEMO[key] = plan
            while len(_GATE_MEMO) > _MEMO_CAP:
                _GATE_MEMO.popitem(last=False)
        STAT_ADD("analysis.mem_plans")
        STAT_SET("analysis.mem_peak_bytes", plan.peak_bytes)

    res = plan.findings()
    if mode == "error":
        if res.errors():
            STAT_ADD("analysis.mem_gate_rejects")
            res.raise_if_errors()
        if fresh and res.findings:
            _warn_once(where, res)
    elif fresh and res.findings:
        _warn_once(where, res)
    return plan


def _warn_once(where, res):
    import warnings
    warnings.warn(f"[{where}] memory analysis: {res.summary()} "
                  f"(FLAGS_memory_gate; see docs/memory_planning.md)")

"""fluid.average — WeightedAverage (reference: python/paddle/fluid/
average.py): host-side running weighted mean over fetched numpy values,
used by the book tutorials for epoch-level loss/accuracy reporting."""
from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        # elementwise like the reference: an ndarray value accumulates
        # per element (epoch-averaging a fetched per-sample vector)
        arr = np.asarray(value, dtype=np.float64)
        self.numerator = self.numerator + arr * weight
        self.denominator += weight

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError(
                "There is no data to be averaged in WeightedAverage.")
        out = self.numerator / self.denominator
        return float(out) if np.ndim(out) == 0 else out

"""Deployment stack (reference paddle/fluid/inference/, SURVEY.md §2.6).

The reference ships a separate C++ predictor with an analysis-pass
pipeline and subgraph engines (TensorRT/Anakin/nGraph). On TPU the engine
IS the compiler: a saved inference program (io.save_inference_model)
lowers whole to one XLA computation, and `AnalysisPredictor` caches the
compiled executable per input-shape set. `export_stablehlo` produces the
portable AOT serving artifact. The C-ABI surface lives in native/src/
(runtime data feed / buffers); program+params files are
JSON + npz, loadable from any language.
"""
from .api import (AnalysisConfig, AnalysisPredictor,  # noqa: F401
                  PaddleTensor, ZeroCopyTensor, create_paddle_predictor)

__all__ = ["AnalysisConfig", "AnalysisPredictor", "PaddleTensor",
           "ZeroCopyTensor", "create_paddle_predictor"]

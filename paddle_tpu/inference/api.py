"""Inference/serving API.

Reference: paddle/fluid/inference/api/ — `AnalysisConfig` +
`AnalysisPredictor` (analysis_predictor.cc:78 Init, :223 Run, :461
OptimizeInferenceProgram, :478 factory) with the ZeroCopyTensor interface,
over a pass-managed optimized program.

TPU-native mapping: the "analysis" phase is program pruning to the
feed→fetch slice (done at save time, io.py) plus whole-program XLA
compilation — constant folding, fusion and memory planning are XLA passes,
so there is no separate pass manager to re-implement (the reference's
nGraph/TensorRT subgraph engines are precedent; here the subgraph is
always the whole program). AOT deployment exports the compiled function
as portable StableHLO (`export_stablehlo`), the serving-artifact analogue
of the reference's serialized TensorRT engines.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

__all__ = ["AnalysisConfig", "AnalysisPredictor", "PaddleTensor",
           "ZeroCopyTensor", "create_paddle_predictor"]


class AnalysisConfig:
    """Knob-compatible subset of paddle_analysis_config.h."""

    def __init__(self, model_dir: Optional[str] = None,
                 prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._model_dir = model_dir
        self._prog_file = prog_file
        self._params_file = params_file
        self._use_feed_fetch_ops = True
        self._ir_optim = True
        self._memory_optim = True
        self._use_device = "tpu"
        self._math_threads = 1

    # -- model location -------------------------------------------------
    def set_model(self, x, y=None):
        if y is None:
            self._model_dir = x
        else:
            self._prog_file, self._params_file = x, y

    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    # -- toggles (XLA owns the optimizations these gated) ---------------
    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def switch_use_feed_fetch_ops(self, flag=True):
        self._use_feed_fetch_ops = flag

    def disable_gpu(self):
        self._use_device = "cpu"

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_device = "tpu"  # device parity: the accelerator is TPU

    def enable_tensorrt_engine(self, **kw):
        raise NotImplementedError(
            "TensorRT does not exist on TPU; the whole program is one XLA "
            "computation already (see module docstring)")

    def use_gpu(self):
        return self._use_device == "tpu"

    def set_cpu_math_library_num_threads(self, n):
        self._math_threads = n


class PaddleTensor:
    """Input/output value for Predictor.run (paddle_api.h PaddleTensor)."""

    def __init__(self, data=None, name=""):
        self.name = name
        self.data = np.asarray(data) if data is not None else None

    @property
    def shape(self):
        return list(self.data.shape)

    def as_ndarray(self):
        return self.data


class ZeroCopyTensor:
    """Handle bound to one predictor input/output slot
    (zero_copy_tensor.cc): copy_from_cpu stages the feed, copy_to_cpu
    reads the result after zero_copy_run."""

    def __init__(self, name, store: Dict[str, np.ndarray]):
        self._name = name
        self._store = store

    def copy_from_cpu(self, arr):
        self._store[self._name] = np.asarray(arr)

    def copy_to_cpu(self):
        return self._store[self._name]

    def reshape(self, shape):
        pass  # shapes are taken from the staged array

    @property
    def name(self):
        return self._name


class AnalysisPredictor:
    def __init__(self, config: AnalysisConfig, _share_from=None):
        from ..core.scope import Scope
        from ..executor import Executor
        from .. import io as fio

        self.config = config
        if _share_from is not None:
            # clone(): share the loaded program, the scope holding the
            # weights, and the Executor (and thereby its executable
            # cache) — the reference predictor clone shares the
            # optimized program and weights the same way. Only the
            # ZeroCopy staging dicts are per-clone.
            self._scope = _share_from._scope
            self._exe = _share_from._exe
            self._program = _share_from._program
            self._feed_names = list(_share_from._feed_names)
            self._fetch_names = list(_share_from._fetch_names)
            self._fetch_vars = _share_from._fetch_vars
            self._inputs: Dict[str, np.ndarray] = {}
            self._outputs: Dict[str, np.ndarray] = {}
            return
        self._scope = Scope()
        self._exe = Executor()
        d = config.model_dir()
        model_file = params_file = None
        if d is None:
            # combined-file form: set_model(prog_file, params_file)
            pf = config.prog_file()
            if pf is None:
                raise ValueError(
                    "AnalysisConfig needs set_model(model_dir) or "
                    "set_model(prog_file, params_file)")
            d = os.path.dirname(pf) or "."
            model_file = os.path.basename(pf)
            params_file = os.path.basename(config.params_file()) \
                if config.params_file() else None
        from ..core.scope import scope_guard
        with scope_guard(self._scope):
            self._program, self._feed_names, fetch_vars = \
                fio.load_inference_model(d, self._exe,
                                         model_filename=model_file,
                                         params_filename=params_file)
        self._fetch_names = [v.name for v in fetch_vars]
        self._fetch_vars = fetch_vars
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}

    # -- PaddleTensor path (analysis_predictor.cc:223 Run) --------------
    def run(self, inputs: List[PaddleTensor]) -> List[PaddleTensor]:
        feed = {}
        for i, t in enumerate(inputs):
            name = t.name or self._feed_names[i]
            feed[name] = t.data
        outs = self.run_dict(feed)
        return [PaddleTensor(o, n)
                for o, n in zip(outs, self._fetch_names)]

    def run_dict(self, feed: Dict[str, np.ndarray]) -> List[np.ndarray]:
        """Dict-feed entry point (the serving engine's worker path):
        {input name: ndarray} -> fetch outputs in get_output_names()
        order."""
        return self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_names,
                             scope=self._scope)

    # -- ZeroCopy path --------------------------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_tensor(self, name):
        return ZeroCopyTensor(name, self._inputs)

    def get_output_tensor(self, name):
        return ZeroCopyTensor(name, self._outputs)

    def zero_copy_run(self):
        outs = self._exe.run(self._program, feed=dict(self._inputs),
                             fetch_list=self._fetch_names,
                             scope=self._scope)
        for n, o in zip(self._fetch_names, outs):
            self._outputs[n] = np.asarray(o)

    def clone(self):
        """A predictor over the SAME loaded program, weights and
        compiled-executable cache (reference analysis_predictor.cc
        Clone shares the optimized program + scope). Clones re-read
        nothing from disk and a shape either predictor already served
        is a cache hit for the other."""
        return AnalysisPredictor(self.config, _share_from=self)

    def program(self):
        return self._program

    # -- AOT export (TPU-native deploy artifact) ------------------------
    def export_stablehlo(self, path: str, example_feed: Dict[str, np.ndarray]):
        """Serialize the compiled feed→fetch computation as StableHLO
        (jax.export): a self-contained, runtime-loadable serving artifact —
        params are baked in as constants, no Python/Program needed at
        serving time."""
        import jax
        from jax import export as jexport
        import jax.numpy as jnp

        from ..core.lowering import LowerCtx, lower_block

        block = self._program.global_block()
        params = {n: jnp.asarray(self._scope.get(n))
                  for n in self._scope.names()}
        fetch_names = self._fetch_names
        # Positional order of the exported callable follows the
        # predictor's declared feed order (NOT sorted(example_feed):
        # sorting silently permuted inputs for callers feeding
        # positionally after deserialization).
        missing = [n for n in self._feed_names if n not in example_feed]
        extra = [n for n in example_feed if n not in self._feed_names]
        if missing or extra:
            raise ValueError(
                f"example_feed must cover exactly the model inputs "
                f"{self._feed_names}; missing {missing}, extra {extra}")
        feed_names = list(self._feed_names)

        def fn(*feeds):
            env = dict(params)
            env.update(zip(feed_names, feeds))
            ctx = LowerCtx(jax.random.PRNGKey(0), is_test=True)
            lower_block(block, env, ctx)
            return tuple(env[n] for n in fetch_names)

        args = tuple(jnp.asarray(example_feed[n]) for n in feed_names)
        exported = jexport.export(jax.jit(fn))(*args)
        blob = exported.serialize()
        with open(path, "wb") as f:
            f.write(blob)
        return {"feed_names": feed_names, "fetch_names": fetch_names,
                "bytes": len(blob)}


def create_paddle_predictor(config: AnalysisConfig) -> AnalysisPredictor:
    """Factory (analysis_predictor.cc:478 CreatePaddlePredictor)."""
    return AnalysisPredictor(config)

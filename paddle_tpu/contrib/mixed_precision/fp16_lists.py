"""Mixed-precision op lists (reference: contrib/mixed_precision/
fp16_lists.py). On TPU the low-precision type is bfloat16 (the MXU's native
input type) rather than float16; bf16's fp32-equal exponent range is also
why loss scaling defaults off here.
"""
from __future__ import annotations

# Ops that should run in bf16: matmul/conv-family — the MXU work.
white_list = {
    "mul", "matmul", "matmul_v2", "conv2d", "conv3d", "depthwise_conv2d",
    "conv2d_transpose",
    # fused attention kernels: bf16 operands hit the MXU fast path, all
    # softmax/accumulation math stays f32 inside the kernel
    "flash_attention", "ring_attention", "ulysses_attention",
}

# Ops that must stay fp32 for numerics: reductions into losses, norms.
black_list = {
    "softmax_with_cross_entropy", "cross_entropy", "cross_entropy2",
    "mean", "reduce_mean", "reduce_sum", "layer_norm", "batch_norm",
    "instance_norm", "group_norm", "softmax", "log_softmax", "exp", "log",
    "sum", "squared_l2_norm", "sigmoid_cross_entropy_with_logits",
}

# Everything else ("gray"): runs in whatever dtype arrives.
gray_list = None


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)

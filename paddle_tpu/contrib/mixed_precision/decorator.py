"""AMP decorator: bf16 program rewrite + (optional) loss scaling.

Reference: contrib/mixed_precision/decorator.py:53
OptimizerWithMixedPrecision — rewrites the forward program to fp16 per
black/white lists (rewrite_program), scales the loss, unscales grads, and
maintains dynamic loss-scaling state (decorator.py:62-69).

TPU differences by design:
- the low-precision type is bfloat16: same exponent range as fp32, so
  loss scaling is OFF by default (init_loss_scaling=1.0) and dynamic
  scaling exists only for API compatibility;
- master weights stay fp32 in the Scope; cast ops inserted before
  white-list ops produce bf16 operands, and the vjp of cast
  automatically returns fp32 gradients to the params — no separate
  master-weight copy pass is needed.
"""
from __future__ import annotations

from ...backward import append_backward
from ...framework import default_main_program, unique_name
from .fp16_lists import AutoMixedPrecisionLists

__all__ = ["decorate", "OptimizerWithMixedPrecision", "rewrite_program"]


def _cast_var(block, name, dst_dtype, cache):
    key = (name, dst_dtype)
    if key in cache:
        return cache[key]
    src = block.var(name)
    out_name = unique_name.generate(f"{name}.cast_{dst_dtype}")
    block.create_var(name=out_name, shape=src.shape, dtype=dst_dtype,
                     stop_gradient=src.stop_gradient)
    from ...framework import Operator
    cast_op = Operator(block, "cast", {"X": [name]}, {"Out": [out_name]},
                       {"out_dtype": dst_dtype})
    cache[key] = (out_name, cast_op)
    return cache[key]


def rewrite_program(main_prog, amp_lists=None):
    """Insert casts so white-list ops consume bf16 and black-list ops
    consume fp32. Operates on the forward program in place, before
    backward is appended (grads then flow through the cast vjps)."""
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    block = main_prog.global_block()
    cache = {}
    new_ops = []
    # dtype environment: var name -> current dtype as ops execute
    dtype_env = {n: v.dtype for n, v in block.vars.items()}

    added_casts = set()

    def mark_outputs(op, dtype):
        for n in op.output_names():
            if n and dtype_env.get(n) == "float32" and dtype == "bfloat16":
                dtype_env[n] = "bfloat16"
                v = block._find_var_recursive(n)
                if v is not None:
                    v.dtype = "bfloat16"

    for op in block.ops:
        if op.type in amp_lists.white_list:
            want = "bfloat16"
        elif op.type in amp_lists.black_list:
            want = "float32"
        else:
            # gray op: jnp promotion — output is bf16 only when every
            # float input is bf16 (bf16+fp32 promotes to fp32)
            fdts = [dtype_env.get(n, block.var(n).dtype)
                    for n in op.input_names() if n
                    and dtype_env.get(n, block.var(n).dtype)
                    in ("float32", "bfloat16")]
            if fdts and all(d == "bfloat16" for d in fdts):
                mark_outputs(op, "bfloat16")
            new_ops.append(op)
            continue
        for slot, names in op.inputs.items():
            for i, n in enumerate(names):
                if not n:
                    continue
                cur = dtype_env.get(n, block.var(n).dtype)
                if cur == want or cur not in ("float32", "bfloat16"):
                    continue
                out_name, cast_op = _cast_var(block, n, want, cache)
                if id(cast_op) not in added_casts:
                    added_casts.add(id(cast_op))
                    new_ops.append(cast_op)
                names[i] = out_name
                dtype_env[out_name] = want
        new_ops.append(op)
        # white-list outputs become bf16 (lowerings keep input dtype)
        mark_outputs(op, want)
    block.ops = new_ops
    main_prog._fp_cache = None
    return main_prog


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=1.0,
                 use_dynamic_loss_scaling=False, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.8):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._loss_scaling = float(init_loss_scaling)
        # bf16 has fp32 range: dynamic loss scaling kept for source compat
        # but degenerates to static scaling.
        self._use_dynamic = use_dynamic_loss_scaling

    def get_loss_scaling(self):
        return self._loss_scaling

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        from ... import layers
        rewrite_program(loss.block.program, self._amp_lists)
        scaled = loss
        if self._loss_scaling != 1.0:
            scaled = layers.scale(loss, scale=self._loss_scaling)
        params_grads = append_backward(scaled, parameter_list, no_grad_set,
                                       callbacks)
        if self._loss_scaling != 1.0:
            inv = 1.0 / self._loss_scaling
            params_grads = [(p, layers.scale(g, scale=inv))
                            for p, g in params_grads]
        return params_grads

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        opt_ops = self._optimizer.apply_gradients(params_grads)
        return opt_ops, params_grads


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=False):
    """fluid.contrib.mixed_precision.decorate (decorator.py:447)."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio)

"""Quantization-aware training: program rewrite inserting fake-quant ops.

Reference: contrib/slim/quantization/quantization_pass.py
(QuantizationTransformPass) — for each quantizable op (conv2d, mul,
depthwise_conv2d), quantize its activation input (moving-average abs-max)
and weight (channel-wise abs-max); gradients pass straight through (STE).
The same rewrite here operates on the Program IR directly; the fake-quant
ops lower to round/clip which XLA fuses into the surrounding computation.
"""
from __future__ import annotations

from ...framework import Operator, unique_name

__all__ = ["QuantizationTransformPass", "quant_aware"]

QUANTIZABLE = {"conv2d": ("Input", "Filter"), "depthwise_conv2d":
               ("Input", "Filter"), "mul": ("X", "Y"),
               "matmul": ("X", "Y")}


class QuantizationTransformPass:
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 moving_rate=0.9, skip_pattern="skip_quant"):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_type = activation_quantize_type
        self.weight_type = weight_quantize_type
        self.moving_rate = moving_rate
        self.skip_pattern = skip_pattern

    def _skipped(self, op):
        if not self.skip_pattern:
            return False
        for names in list(op.inputs.values()) + list(op.outputs.values()):
            if any(self.skip_pattern in n for n in names if n):
                return True
        return False

    def apply(self, program, startup_program=None):
        block = program.global_block()
        new_ops = []
        quant_cache = {}
        for op in block.ops:
            if op.type in QUANTIZABLE and not self._skipped(op):
                act_slot, w_slot = QUANTIZABLE[op.type]
                for slot in (act_slot, w_slot):
                    names = op.inputs.get(slot, [])
                    for i, n in enumerate(names):
                        if not n:
                            continue
                        v = block.var(n)
                        if v.dtype not in ("float32", "bfloat16"):
                            continue
                        # weight-quantize only real parameters; a matmul Y
                        # that is an activation (attention K/V) gets the
                        # activation scheme (reference only quantizes
                        # persistable weights channel-wise)
                        is_weight = slot == w_slot and \
                            getattr(v, "persistable", False)
                        # output-channel axis: conv filters [O,I,kh,kw]→0,
                        # fc/mul/matmul weights [in,out]→last
                        qaxis = 0 if op.type in ("conv2d",
                                                 "depthwise_conv2d") \
                            else len(v.shape) - 1
                        qn = self._insert_quant(block, new_ops, n,
                                                is_weight, quant_cache,
                                                startup_program, qaxis)
                        names[i] = qn
            new_ops.append(op)
        block.ops = new_ops
        program._fp_cache = None
        return program

    def _insert_quant(self, block, new_ops, name, is_weight, cache,
                      startup_program, quant_axis=0):
        if name in cache:
            return cache[name]
        v = block.var(name)
        out = unique_name.generate(f"{name}.quantized")
        block.create_var(name=out, shape=v.shape, dtype=v.dtype,
                         stop_gradient=v.stop_gradient)
        scale = unique_name.generate(f"{name}.scale")
        if is_weight and self.weight_type == "channel_wise_abs_max":
            block.create_var(name=scale, shape=(v.shape[quant_axis],),
                             dtype="float32", stop_gradient=True)
            qop = Operator(block, "fake_channel_wise_quantize_abs_max",
                           {"X": [name]},
                           {"Out": [out], "OutScale": [scale]},
                           {"bit_length": self.weight_bits,
                            "quant_axis": quant_axis})
            # reference QuantizationTransformPass pairs every quant op
            # (integer-grid output) with its dequant op; the consumer
            # reads the dequantized float value
            new_ops.append(qop)
            out = self._insert_dequant(
                block, new_ops, out, name,
                "fake_channel_wise_dequantize_max_abs",
                {"Scales": [scale]},
                {"quant_bits": [self.weight_bits],
                 "quant_axis": quant_axis})
            cache[name] = out
            return out
        elif is_weight or self.act_type == "abs_max":
            block.create_var(name=scale, shape=(1,), dtype="float32",
                            stop_gradient=True)
            bits = self.weight_bits if is_weight else self.activation_bits
            qop = Operator(block, "fake_quantize_abs_max", {"X": [name]},
                           {"Out": [out], "OutScale": [scale]},
                           {"bit_length": bits})
            new_ops.append(qop)
            out = self._insert_dequant(
                block, new_ops, out, name, "fake_dequantize_max_abs",
                {"Scale": [scale]},
                {"max_range": float((1 << (bits - 1)) - 1)})
            cache[name] = out
            return out
        else:
            # moving-average activation quant: persistent scale + ema state;
            # at eval (is_test flipped by clone(for_test=True)) the op reads
            # the calibrated InScale and freezes the moving averages.
            scale = self._state_var(block, f"{name}.scale", startup_program,
                                    init=1.0)
            state = self._state_var(block, f"{name}.qstate",
                                    startup_program)
            accum = self._state_var(block, f"{name}.qaccum",
                                    startup_program)
            qop = Operator(
                block, "fake_quantize_dequantize_moving_average_abs_max",
                {"X": [name], "InScale": [scale], "InState": [state],
                 "InAccum": [accum]},
                {"Out": [out], "OutScale": [scale], "OutState": [state],
                 "OutAccum": [accum]},
                {"bit_length": self.activation_bits,
                 "moving_rate": self.moving_rate, "is_test": False})
        new_ops.append(qop)
        cache[name] = out
        return out

    def _insert_dequant(self, block, new_ops, quantized, orig_name,
                        op_type, extra_ins, attrs):
        out = unique_name.generate(f"{orig_name}.dequantized")
        qv = block.var(quantized)
        block.create_var(name=out, shape=qv.shape, dtype=qv.dtype,
                         stop_gradient=qv.stop_gradient)
        new_ops.append(Operator(block, op_type,
                                {"X": [quantized], **extra_ins},
                                {"Out": [out]}, attrs))
        return out

    def _state_var(self, block, hint, startup_program, init=0.0):
        from ...initializer import Constant
        name = unique_name.generate(hint)
        block.create_var(name=name, shape=(1,), dtype="float32",
                         persistable=True, stop_gradient=True)
        if startup_program is not None:
            sb = startup_program.global_block()
            sv = sb.create_var(name=name, shape=(1,), dtype="float32",
                               persistable=True, stop_gradient=True)
            Constant(init)(sv, sb)
        return name


def quant_aware(program, startup_program=None, weight_bits=8,
                activation_bits=8):
    """One-call QAT rewrite (paddleslim-style convenience)."""
    return QuantizationTransformPass(
        weight_bits, activation_bits).apply(program, startup_program)

"""Structured pruning over the Program IR + Scope.

Reference: contrib/slim/prune/pruner.py (StructurePruner: group-sort by
l1_norm along a pruning axis, drop the lowest-ratio groups) and
prune_strategy.py (_prune_parameters: walk the graph so downstream
consumers of a pruned output-channel axis are pruned consistently).

TPU-first design: XLA compiles static shapes, so two modes exist —

- mask mode (``lazy=True``): pruned groups are ZEROED in the Scope.
  Shapes (and therefore the compiled executable) are unchanged, the
  sparsity is recoverable by finetuning, and the same program keeps
  running. This is the mode to use mid-training.
- shrink mode (``lazy=False``): parameters are physically sliced and the
  program's var shapes rewritten, producing a smaller model + a fresh
  compile. Downstream dependents (the next matmul/conv input axis,
  batch-norm scale/bias/mean/variance) are pruned to match, following
  the reference's graph walk.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Pruner", "StructurePruner", "prune_program"]


class Pruner:
    """Base class (reference pruner.py:22)."""

    def prune(self, param):
        raise NotImplementedError


class StructurePruner(Pruner):
    """Group pruning by axis + criterion (reference pruner.py:33)."""

    def __init__(self, pruning_axis=None, criterions=None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        """Indices of the lowest-criterion groups along axis
        (reference pruner.py:55)."""
        criterion = self.criterions.get(name, self.criterions["*"])
        if axis is None:
            axis = self.pruning_axis.get(name, self.pruning_axis["*"])
        prune_num = int(round(param.shape[axis] * ratio))
        reduce_dims = tuple(i for i in range(param.ndim) if i != axis)
        if criterion != "l1_norm":
            raise ValueError(f"unsupported criterion {criterion!r}")
        scores = np.sum(np.abs(param), axis=reduce_dims)
        return np.argsort(scores)[:prune_num]

    def prune_tensor(self, tensor, pruned_idx, pruned_axis, lazy=False):
        """Zero (lazy) or slice out (shrink) the given groups
        (reference pruner.py:82)."""
        if lazy:
            out = np.array(tensor)
            sl = [slice(None)] * out.ndim
            sl[pruned_axis] = pruned_idx
            out[tuple(sl)] = 0.0
            return out
        mask = np.ones(tensor.shape[pruned_axis], bool)
        mask[pruned_idx] = False
        return np.take(tensor, np.where(mask)[0], axis=pruned_axis)


# ---------------------------------------------------------------------------
# program-level one-shot pruning (reference prune_strategy.py)
# ---------------------------------------------------------------------------

# how an op consumes a var whose producer axis-0 was pruned:
# op type -> (weight slot, input-channel axis of that weight)
_CONSUMER_AXIS = {"mul": ("Y", 0), "matmul": ("Y", 0), "fc": ("W", 0),
                  "conv2d": ("Filter", 1)}
# ops whose per-channel params follow the producer's pruned axis
_CHANNEL_FOLLOWERS = {"batch_norm": ("Scale", "Bias", "Mean", "Variance")}
# ops that consume/reduce the channel axis — the walk legitimately ends
_TERMINAL = {"softmax_with_cross_entropy", "cross_entropy",
             "cross_entropy2", "mean", "reduce_mean", "reduce_sum",
             "accuracy", "mse_loss", "square_error_cost",
             "sigmoid_cross_entropy_with_logits", "fetch", "feed",
             "auc", "top_k"}
# shape-preserving on the channel axis: the walk continues through them
# (softmax keeps the axis — anything consuming its output still needs
# consistent pruning)
_PASSTHROUGH = {"relu", "sigmoid", "tanh", "gelu", "dropout", "pool2d",
                "scale", "relu6", "leaky_relu", "softmax"}


def _producer_out(op):
    for slot in ("Out", "Output", "Y"):
        names = op.outputs.get(slot)
        if names and names[0]:
            return names[0]
    return None


def prune_program(program, scope, params, ratios, pruner=None,
                  lazy=False):
    """Prune named parameters by ratio and keep the program consistent.

    params: list of parameter names (conv Filter / fc W) to prune along
    their output axis — axis 0 (output channels) for conv filters,
    axis 1 (output features) for fc/mul weights — determined from the
    op that owns the parameter. Returns {param_name: pruned_idx}.
    """
    pruner = pruner or StructurePruner()
    block = program.global_block()
    pruned = {}

    for pname, ratio in zip(params, ratios):
        # find the op consuming this parameter as a weight
        owner, w_axis, out_name = None, None, None
        for op in block.ops:
            if op.type in ("conv2d", "depthwise_conv2d") and \
                    pname in op.inputs.get("Filter", []):
                owner, w_axis = op, 0        # output channels
            elif op.type in ("mul", "matmul") and \
                    pname in op.inputs.get("Y", []):
                owner, w_axis = op, 1        # output features
            elif op.type == "fc" and pname in op.inputs.get("W", []):
                owner, w_axis = op, 1
            if owner is not None:
                out_name = _producer_out(owner)
                break
        if owner is None:
            raise ValueError(f"parameter {pname!r} is not a conv/fc "
                             f"weight in this program")

        w = scope.get_numpy(pname)
        idx = pruner.cal_pruned_idx(pname, w, ratio, axis=w_axis)
        pruned[pname] = idx
        _prune_shaped(block, scope, pruner, pname, idx, w_axis, lazy)

        # bias of the same op follows the pruned output axis
        for bslot in ("Bias",):
            bnames = owner.inputs.get(bslot, [])
            if bnames and bnames[0] and scope.has(bnames[0]):
                ax = scope.get_numpy(bnames[0]).ndim - 1
                _prune_shaped(block, scope, pruner, bnames[0], idx, ax,
                              lazy)

        # walk downstream consumers of the pruned output
        _prune_consumers(block, scope, pruner, out_name, idx, lazy,
                         dim=w.shape[w_axis], _seen=set())
    if not lazy:
        program._fp_cache = None
    return pruned


def _prune_shaped(block, scope, pruner, name, idx, ax, lazy):
    t = scope.get_numpy(name)
    scope.set(name, pruner.prune_tensor(t, idx, ax, lazy))
    if not lazy:
        v = block.var(name)
        s = list(v.shape)
        s[ax] -= len(idx)
        v.shape = s


def _prune_consumers(block, scope, pruner, var_name, idx, lazy, dim,
                     _depth=0, _seen=None):
    """Follow the pruned producer output through its consumers; `dim` is
    the pre-prune size of the pruned axis (identifies broadcast biases).
    `_seen` guards diamonds (an op or weight reached via two branches
    must be pruned once). In shrink mode an op the walk cannot classify
    raises — leaving its weight unpruned would ship a shape-inconsistent
    program; in mask (lazy) mode downstream pruning is an optimization
    (masked units already emit zeros once their bias is zeroed), so the
    walk just stops there."""
    if var_name is None:
        return
    if _depth > 32:
        raise RuntimeError(
            f"prune walk exceeded depth 32 at var {var_name!r}; "
            f"downstream consumers would be left inconsistent")
    _seen = _seen if _seen is not None else set()
    for op in block.ops:
        in_names = [n for names in op.inputs.values() for n in names]
        if var_name not in in_names or id(op) in _seen:
            continue
        _seen.add(id(op))
        if op.type == "depthwise_conv2d":
            # depthwise filter is [C*mult, 1, kh, kw]; only channel
            # multiplier 1 maps pruned input channels 1:1 onto filter
            # rows and output channels
            wn = op.inputs.get("Filter", [None])[0]
            if wn and scope.has(wn) and ("w", wn) not in _seen:
                wshape = scope.get_numpy(wn).shape
                if wshape[0] != dim:
                    if not lazy:
                        raise RuntimeError(
                            f"shrink-mode prune cannot handle depthwise "
                            f"filter {wn!r} with channel multiplier "
                            f"{wshape[0] // dim} (filter rows "
                            f"{wshape[0]} != channels {dim})")
                    continue
                if ("w", wn) not in _seen:
                    _seen.add(("w", wn))
                    _prune_shaped(block, scope, pruner, wn, idx, 0, lazy)
            _prune_consumers(block, scope, pruner, _producer_out(op),
                             idx, lazy, dim, _depth + 1, _seen)
        elif op.type in _CONSUMER_AXIS:
            slot, ax = _CONSUMER_AXIS[op.type]
            wn = op.inputs.get(slot, [None])[0]
            if wn and scope.has(wn) and ("w", wn) not in _seen:
                _seen.add(("w", wn))
                _prune_shaped(block, scope, pruner, wn, idx, ax, lazy)
        elif op.type in _CHANNEL_FOLLOWERS:
            for slot in _CHANNEL_FOLLOWERS[op.type]:
                nn = op.inputs.get(slot, [None])[0]
                if nn and scope.has(nn) and ("w", nn) not in _seen:
                    _seen.add(("w", nn))
                    _prune_shaped(block, scope, pruner, nn, idx, 0, lazy)
            # bn output carries the pruned channel axis onward
            _prune_consumers(block, scope, pruner, _producer_out(op),
                             idx, lazy, dim, _depth + 1, _seen)
        elif op.type in ("elementwise_add", "elementwise_sub",
                         "elementwise_mul"):
            # a broadcast 1-D persistable operand (fc bias, scale vector)
            # rides the pruned axis and must follow it
            for n in in_names:
                if n == var_name or not scope.has(n) or \
                        ("w", n) in _seen:
                    continue
                t = scope.get_numpy(n)
                if t.ndim == 1 and t.shape[0] == dim:
                    _seen.add(("w", n))
                    _prune_shaped(block, scope, pruner, n, idx, 0, lazy)
            _prune_consumers(block, scope, pruner, _producer_out(op),
                             idx, lazy, dim, _depth + 1, _seen)
        elif op.type in _PASSTHROUGH:
            _prune_consumers(block, scope, pruner, _producer_out(op),
                             idx, lazy, dim, _depth + 1, _seen)
        elif op.type in _TERMINAL:
            pass  # channel axis is consumed here; nothing to prune
        elif not lazy:
            raise RuntimeError(
                f"shrink-mode prune walk cannot classify op "
                f"{op.type!r} consuming {var_name!r}; its weights would "
                f"be left shape-inconsistent (use lazy=True mask "
                f"pruning, or extend the walk tables)")

"""Knowledge distillation: merge a teacher program into the student's and
attach distillation losses.

Reference: contrib/slim/distillation/distiller.py (L2Distiller :25,
FSPDistiller :103, SoftLabelDistiller :195 — each builds a *Pass that
appends its loss subgraph onto the merged graph) and the
DistillationStrategy that merges teacher/student programs.

Here the merge clones the teacher's ops into the student program with a
name prefix (shared feed vars are mapped, not cloned), copies teacher
weights into the scope under the prefixed names with stop_gradient so
only the student trains, and the distillers emit ordinary IR ops — the
whole distillation step stays ONE XLA computation.
"""
from __future__ import annotations

import numpy as np

__all__ = ["merge", "L2Distiller", "SoftLabelDistiller", "FSPDistiller"]

PREFIX = "teacher_"


def merge(teacher_program, student_program, data_name_map=None,
          scope=None, teacher_scope=None, name_prefix=PREFIX):
    """Clone teacher ops/vars into the student program.

    data_name_map: {teacher_feed_name: student_feed_name} — those vars
    are shared instead of cloned. Teacher vars are renamed with
    name_prefix and marked stop_gradient (the reference merge sets
    teacher vars untrainable). Teacher parameter values are copied from
    teacher_scope (default: same scope) under the new names.
    """
    from ...core.scope import global_scope
    data_name_map = dict(data_name_map or {})
    scope = scope or global_scope()
    teacher_scope = teacher_scope or scope

    t_block = teacher_program.global_block()
    s_block = student_program.global_block()

    def map_name(n):
        if not n:
            return n
        return data_name_map.get(n, name_prefix + n)

    for v in t_block.vars.values():
        if v.name in data_name_map:
            continue
        nn = name_prefix + v.name
        if not s_block.has_var(nn):
            s_block.create_var(name=nn, shape=v.shape, dtype=v.dtype,
                               persistable=v.persistable,
                               stop_gradient=True)
        if teacher_scope.has(v.name) and v.persistable:
            scope.set(nn, teacher_scope.get_numpy(v.name))

    for op in t_block.ops:
        if op.type in ("feed", "fetch"):
            continue
        ins = {s: [map_name(n) for n in names]
               for s, names in op.inputs.items()}
        outs = {s: [map_name(n) for n in names]
                for s, names in op.outputs.items()}
        attrs = dict(op.attrs)
        attrs["is_test"] = True  # teacher always runs in inference mode
        s_block.append_op(op.type, inputs=ins, outputs=outs, attrs=attrs)
    student_program._fp_cache = None
    return student_program


def _teacher_var(block, name):
    """Resolve a teacher feature map: merge() renames teacher vars with
    PREFIX, but maps derived inside the student program (e.g. reshapes
    of merged vars) already carry their final name."""
    if block.has_var(PREFIX + name):
        return block.var(PREFIX + name)
    return block.var(name)


class L2Distiller:
    """L2 loss between a student and a teacher feature map
    (reference distiller.py:25)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, program):
        from ... import layers
        from ...framework import program_guard
        with program_guard(program):
            block = program.global_block()
            s = block.var(self.student_feature_map)
            t = _teacher_var(block, self.teacher_feature_map)
            loss = layers.reduce_mean(layers.square(
                layers.elementwise_sub(s, t)))
            return layers.scale(loss, scale=float(self.weight))


class SoftLabelDistiller:
    """Soft-target cross entropy between softened logits
    (reference distiller.py:195)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, program):
        from ... import layers
        from ...framework import program_guard
        with program_guard(program):
            block = program.global_block()
            s = block.var(self.student_feature_map)
            t = _teacher_var(block, self.teacher_feature_map)
            s_soft = layers.softmax(
                layers.scale(s, scale=1.0 / self.student_temperature))
            t_soft = layers.softmax(
                layers.scale(t, scale=1.0 / self.teacher_temperature))
            ce = layers.cross_entropy(s_soft, t_soft, soft_label=True)
            return layers.scale(layers.reduce_mean(ce),
                                scale=float(self.weight))


class FSPDistiller:
    """Flow-of-solution-procedure matrices L2 loss
    (reference distiller.py:103; uses the fsp op, fsp_op.cc)."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1.0):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.weight = distillation_loss_weight

    def distiller_loss(self, program):
        from ... import layers
        from ...framework import program_guard
        with program_guard(program):
            block = program.global_block()
            losses = []
            for (s0, s1), (t0, t1) in zip(self.student_pairs,
                                          self.teacher_pairs):
                s_fsp = layers.fsp_matrix(block.var(s0), block.var(s1))
                t_fsp = layers.fsp_matrix(_teacher_var(block, t0),
                                          _teacher_var(block, t1))
                losses.append(layers.reduce_mean(layers.square(
                    layers.elementwise_sub(s_fsp, t_fsp))))
            total = losses[0]
            for l in losses[1:]:
                total = layers.elementwise_add(total, l)
            return layers.scale(total, scale=float(self.weight))

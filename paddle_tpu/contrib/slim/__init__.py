"""Model compression (reference: contrib/slim — quantization/prune/NAS/
distillation). Round-1 scope: quantization-aware training (fake-quant
rewrite) + magnitude pruning utilities."""
from . import quantization  # noqa: F401

"""Model compression (reference: contrib/slim — quantization, pruning,
distillation, NAS). Quantization-aware training (fake-quant rewrite),
structured pruning over the Program IR (mask + shrink modes), and
distillation (teacher-program merge + L2/soft-label/FSP losses)."""
from . import distillation  # noqa: F401
from . import nas  # noqa: F401
from . import prune  # noqa: F401
from . import quantization  # noqa: F401

"""Neural architecture search: simulated-annealing controller + search
space + light NAS loop.

Reference: contrib/slim/searcher/controller.py (EvolutionaryController
:28, SAController :59 — token-list states, annealed acceptance of
lower-reward mutations), contrib/slim/nas/search_space.py +
light_nas_strategy.py (tokens -> candidate program, train briefly,
reward = accuracy under a latency/flops constraint). The
controller-server/agent RPC split collapses here: on TPU the search
loop is host-side anyway, so LightNAS drives the controller directly.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["EvolutionaryController", "SAController", "SearchSpace",
           "LightNAS"]


class EvolutionaryController:
    """Token-list search controller base (reference controller.py:28)."""

    def update(self, tokens, reward):
        raise NotImplementedError

    def reset(self, range_table, init_tokens=None, constrain_func=None):
        raise NotImplementedError

    def next_tokens(self):
        raise NotImplementedError


class SAController(EvolutionaryController):
    """Simulated annealing over token lists (reference controller.py:59):
    mutate a fraction of tokens; accept worse rewards with probability
    exp((r_new - r_current) / T) against the last ACCEPTED reward,
    T decaying geometrically."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024.0, max_iter_number=300, seed=0):
        self._range_table = list(range_table or [])
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._rng = np.random.RandomState(seed)
        self._iter = 0
        self._tokens = None
        self._reward = -math.inf
        self._best_tokens = None
        self._best_reward = -math.inf
        self._constrain_func = None

    def reset(self, range_table, init_tokens=None, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens) if init_tokens is not None else \
            [int(self._rng.randint(r)) for r in self._range_table]
        if constrain_func is not None and not constrain_func(self._tokens):
            raise ValueError(
                f"init tokens {self._tokens} violate the constraint "
                f"(e.g. flops budget)")
        self._iter = 0
        self._reward = -math.inf
        self._best_tokens = list(self._tokens)
        self._best_reward = -math.inf
        return self._tokens

    @property
    def best_tokens(self):
        return list(self._best_tokens or [])

    @property
    def max_reward(self):
        return self._best_reward

    def update(self, tokens, reward):
        """Accept/reject `tokens` given its measured reward."""
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        if reward > self._reward or self._rng.rand() < math.exp(
                min((reward - self._reward) / max(temperature, 1e-9),
                    0.0)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._best_reward:
            self._best_reward = reward
            self._best_tokens = list(tokens)

    @property
    def exhausted(self):
        """True once max_iter_number updates have been consumed
        (reference controller.py stop condition)."""
        return self._iter >= self._max_iter_number

    def next_tokens(self):
        """Mutate the current state; respects constrain_func by
        re-sampling (reference SAController.next_tokens)."""
        if self.exhausted:
            raise StopIteration(
                f"SAController exhausted after {self._iter} iterations")
        for _ in range(100):
            cand = list(self._tokens)
            n_mut = max(1, int(len(cand) * 0.3))
            for i in self._rng.choice(len(cand), n_mut, replace=False):
                cand[i] = int(self._rng.randint(self._range_table[i]))
            if self._constrain_func is None or self._constrain_func(cand):
                return cand
        raise RuntimeError(
            "could not find a constraint-satisfying mutation in 100 "
            "attempts; the budget is too tight for this search space")


class SearchSpace:
    """tokens <-> candidate model (reference nas/search_space.py): a
    subclass defines the range table, builds a train program from a
    token list, and scores it."""

    def init_tokens(self):
        raise NotImplementedError

    def range_table(self):
        raise NotImplementedError

    def create_net(self, tokens):
        """-> (startup_program, train_program, loss_var)"""
        raise NotImplementedError

    def flops(self, tokens) -> float:
        return 0.0


class LightNAS:
    """Search loop (reference nas/light_nas_strategy.py): controller
    proposes tokens, the space builds + briefly trains the candidate,
    reward = score under an optional flops budget."""

    def __init__(self, search_space, controller=None, max_flops=None,
                 search_steps=10, train_steps=20, seed=0):
        self.space = search_space
        self.max_flops = max_flops
        self.search_steps = search_steps
        self.train_steps = train_steps
        self.controller = controller or SAController(seed=seed)
        constrain = None
        if max_flops is not None:
            constrain = lambda toks: self.space.flops(toks) <= max_flops
        self.controller.reset(self.space.range_table(),
                              self.space.init_tokens(), constrain)
        self.history = []

    def _evaluate(self, tokens, feed_batches):
        import paddle_tpu as fluid
        startup, train_prog, loss = self.space.create_net(tokens)[:3]
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(startup)
            lv = None
            for i in range(self.train_steps):
                feed = feed_batches[i % len(feed_batches)]
                lv, = exe.run(train_prog, feed=feed, fetch_list=[loss])
        return -float(np.asarray(lv).reshape(()))  # reward = -loss

    def search(self, feed_batches):
        """Run the annealed search; returns (best_tokens, best_reward)."""
        for _ in range(self.search_steps):
            if getattr(self.controller, "exhausted", False):
                break
            tokens = self.controller.next_tokens()
            reward = self._evaluate(tokens, feed_batches)
            self.controller.update(tokens, reward)
            self.history.append((tokens, reward))
        return self.controller.best_tokens, self.controller.max_reward

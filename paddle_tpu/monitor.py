"""Process-global runtime stats registry: counters, gauges, histograms.

Reference: platform/monitor.h — a global STAT registry written with
STAT_ADD/STAT_RESET macros from every subsystem (allocator, RPC,
executor) and drained by periodic exporters — plus the host-phase
aggregation half of platform/profiler.cc. Here the same design carries
the TPU runtime's cost attribution: the executor, reader, and memory
layers record into this module, and two exporters (append-mode JSONL
snapshots, Prometheus text format) plus a chrome-trace event dump get
the data out even when the process is killed mid-run.

Near-zero cost when disabled: every STAT_* entry point checks
FLAGS_enable_monitor through a cached flag handle (one attribute read)
before doing any work, so instrumented hot paths cost ~a function call
when the monitor is off.

Stat names are dotted lowercase (`executor.step_seconds`); the full
inventory lives in docs/observability.md and is lint-enforced by
tests/test_observability.py.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["STAT_ADD", "STAT_SET", "STAT_OBSERVE", "STAT_RESET",
           "enabled", "reset_stats", "reset_phases", "get_stats_snapshot",
           "get_phase_stats", "phase_events", "phase", "push_phase",
           "pop_phase",
           "snapshot_to_jsonl", "prometheus_text", "export_prometheus",
           "export_chrome_tracing", "start_exporter", "stop_exporter",
           "flight_enabled", "flight_record", "flight_step",
           "flight_records", "reset_flight_recorder",
           "dump_flight_recorder", "install_flight_recorder",
           "serve_prometheus", "stop_prometheus",
           "DEFAULT_TIME_BUCKETS"]

# Fixed histogram buckets (upper bounds, seconds): 100us..120s covers a
# feed-copy on one end and a cold XLA compile on the other. The overflow
# bucket is implicit (+inf).
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}
_GAUGES: Dict[str, float] = {}
_HISTS: Dict[str, "_Histogram"] = {}
# Host-phase aggregates (record_event scopes). Separate namespace from
# the STAT registry: phase names are user-provided annotations, not
# inventory-controlled stat names.
_PHASES: Dict[str, Dict[str, float]] = {}
# Recent phase events for chrome-trace export (bounded ring).
_EVENTS: "deque" = deque(maxlen=20000)
_TLS = threading.local()

_flag = None


def enabled() -> bool:
    """FLAGS_enable_monitor, read through a cached flag handle (the
    disabled fast path: one None-check + one attribute read)."""
    global _flag
    f = _flag
    if f is None:
        from .core.flags import flag_handle
        f = _flag = flag_handle("enable_monitor")
    return f.value


class _Histogram:
    __slots__ = ("buckets", "counts", "count", "sum", "min", "max",
                 "exemplars")

    def __init__(self, buckets):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1 overflow
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        # bucket index -> last exemplar (a trace_id): a slow-bucket hit
        # in the snapshot points straight at a kept trace to pull up.
        self.exemplars: Dict[int, str] = {}

    def observe(self, v, exemplar=None):
        v = float(v)
        i = 0
        for b in self.buckets:
            if v <= b:
                break
            i += 1
        self.counts[i] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if exemplar is not None:
            self.exemplars[i] = exemplar

    def percentile(self, q):
        """Estimate from bucket counts: linear interpolation inside the
        target bucket; the overflow bucket clamps to the observed max."""
        if self.count == 0:
            return None
        target = q * self.count
        cum = 0
        lo = 0.0
        for i, c in enumerate(self.counts):
            if cum + c >= target and c > 0:
                hi = self.buckets[i] if i < len(self.buckets) else self.max
                frac = (target - cum) / c
                return min(lo + (hi - lo) * frac, self.max)
            cum += c
            lo = self.buckets[i] if i < len(self.buckets) else self.max
        return self.max

    def to_dict(self):
        b = {}
        for i, c in enumerate(self.counts):
            le = repr(self.buckets[i]) if i < len(self.buckets) else "+inf"
            b[le] = c
        d = {"count": self.count, "sum": self.sum,
             "min": self.min if self.count else None,
             "max": self.max if self.count else None,
             "p50": self.percentile(0.50),
             "p95": self.percentile(0.95),
             "buckets": b}
        if self.exemplars:
            d["exemplars"] = {
                (repr(self.buckets[i]) if i < len(self.buckets)
                 else "+inf"): ex
                for i, ex in sorted(self.exemplars.items())}
        return d


# ---------------------------------------------------------------------------
# Recording API (the STAT_ADD/STAT_RESET surface of platform/monitor.h)
# ---------------------------------------------------------------------------

def STAT_ADD(name: str, value=1):
    """Add to a monotonically-increasing counter (creates on first use)."""
    if not enabled():
        return
    with _LOCK:
        if name in _GAUGES or name in _HISTS:
            raise ValueError(f"stat {name!r} is not a counter")
        _COUNTERS[name] = _COUNTERS.get(name, 0) + value


def STAT_SET(name: str, value):
    """Set a gauge to the latest sampled value."""
    if not enabled():
        return
    with _LOCK:
        if name in _COUNTERS or name in _HISTS:
            raise ValueError(f"stat {name!r} is not a gauge")
        _GAUGES[name] = float(value)


def STAT_OBSERVE(name: str, value, buckets=None, exemplar=None):
    """Record one observation into a fixed-bucket histogram. `buckets`
    (upper bounds, ascending) only applies at first creation; default is
    DEFAULT_TIME_BUCKETS (seconds-oriented). `exemplar` (typically a
    trace_id) is remembered as the last exemplar of the bucket the
    value lands in and surfaces in get_stats_snapshot()."""
    if not enabled():
        return
    with _LOCK:
        if name in _COUNTERS or name in _GAUGES:
            raise ValueError(f"stat {name!r} is not a histogram")
        h = _HISTS.get(name)
        if h is None:
            h = _HISTS[name] = _Histogram(buckets or DEFAULT_TIME_BUCKETS)
        h.observe(value, exemplar=exemplar)


def STAT_RESET(name: Optional[str] = None):
    """Reset one stat (or every stat when name is None). Reference:
    monitor.h STAT_RESET."""
    with _LOCK:
        if name is None:
            _COUNTERS.clear()
            _GAUGES.clear()
            _HISTS.clear()
        else:
            _COUNTERS.pop(name, None)
            _GAUGES.pop(name, None)
            _HISTS.pop(name, None)


def reset_stats(name: Optional[str] = None):
    STAT_RESET(name)


# ---------------------------------------------------------------------------
# Host-phase accounting (profiler.record_event feeds this)
# ---------------------------------------------------------------------------

def push_phase(name: str):
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    # [name, wall-clock start (us), perf start, child time accumulator]
    stack.append([name, time.time() * 1e6, time.perf_counter(), 0.0])


def pop_phase(name: Optional[str] = None):
    stack = getattr(_TLS, "stack", None)
    if not stack:
        return  # unbalanced pop (e.g. reset mid-scope): ignore
    nm, wall_us, start, child = stack.pop()
    total = time.perf_counter() - start
    exclusive = total - child
    if stack:
        stack[-1][3] += total
    with _LOCK:
        agg = _PHASES.setdefault(
            nm, {"count": 0, "total_s": 0.0, "exclusive_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += total
        agg["exclusive_s"] += exclusive
        _EVENTS.append((nm, wall_us, total * 1e6,
                        threading.get_ident()))


@contextlib.contextmanager
def phase(name: str):
    """Scoped host-phase timer. Nested scopes accumulate EXCLUSIVE time
    per phase (a parent's aggregate excludes time spent in children),
    matching the reference profiler's self-time columns."""
    push_phase(name)
    try:
        yield
    finally:
        pop_phase(name)


def get_phase_stats() -> Dict[str, Dict[str, float]]:
    with _LOCK:
        return {k: dict(v) for k, v in _PHASES.items()}


def phase_events() -> list:
    """Point-in-time copy of the recent phase-event ring as
    (name, ts_us, dur_us, tid) tuples — trace.export_chrome_tracing
    merges these with request spans onto one timeline."""
    with _LOCK:
        return list(_EVENTS)


def reset_phases():
    with _LOCK:
        _PHASES.clear()
        _EVENTS.clear()


# ---------------------------------------------------------------------------
# Flight recorder: a bounded ring of per-step records (step index, cache
# hit/miss, timings, stat deltas, NaN provenance) kept in memory and
# dumped as JSONL when the process dies — the crash "black box" the
# aggregate snapshots cannot provide (a counter says HOW MANY NaN trips;
# the flight recorder says WHICH op on WHICH step). Gated by
# FLAGS_flight_recorder (default on: one dict append per step), separate
# from FLAGS_enable_monitor so post-mortems work on unmonitored runs.
# ---------------------------------------------------------------------------

_FLIGHT: "deque" = deque()
_FLIGHT_LOCK = threading.Lock()
_FLIGHT_PREV_COUNTERS: Dict[str, float] = {}
_flight_flag = None


def flight_enabled() -> bool:
    """FLAGS_flight_recorder through a cached flag handle (same
    disabled-fast-path discipline as enabled())."""
    global _flight_flag
    f = _flight_flag
    if f is None:
        from .core.flags import flag_handle
        f = _flight_flag = flag_handle("flight_recorder")
    return f.value


def flight_record(kind: str, **fields):
    """Append one record to the flight-recorder ring (oldest dropped
    past FLAGS_flight_recorder_capacity). Also counts
    `executor.flight_records` when the monitor is enabled."""
    if not flight_enabled():
        return
    from .core.flags import FLAGS
    rec = {"kind": kind, "ts": time.time(), **fields}
    with _FLIGHT_LOCK:
        cap = FLAGS.flight_recorder_capacity
        while cap > 0 and len(_FLIGHT) >= cap:
            _FLIGHT.popleft()
        _FLIGHT.append(rec)
    STAT_ADD("executor.flight_records")


def flight_step(**fields):
    """Record one executor step (Executor.run calls this). When the
    monitor is enabled the record also carries the delta of every
    counter since the previous step record, so a post-mortem shows what
    each step did (bytes fed, cache misses, NaN trips) not just that it
    ran."""
    if not flight_enabled():
        return
    if enabled():
        with _LOCK:
            cur = dict(_COUNTERS)
        with _FLIGHT_LOCK:
            prev = dict(_FLIGHT_PREV_COUNTERS)
            _FLIGHT_PREV_COUNTERS.clear()
            _FLIGHT_PREV_COUNTERS.update(cur)
        delta = {k: v - prev.get(k, 0) for k, v in cur.items()
                 if v != prev.get(k, 0)}
        if delta:
            fields["stats_delta"] = delta
    flight_record("step", **fields)


def flight_records() -> list:
    """Point-in-time copy of the ring (oldest first)."""
    with _FLIGHT_LOCK:
        return list(_FLIGHT)


def reset_flight_recorder():
    with _FLIGHT_LOCK:
        _FLIGHT.clear()
        _FLIGHT_PREV_COUNTERS.clear()


def _default_flight_path() -> str:
    from .core.flags import FLAGS
    return FLAGS.flight_recorder_path or "flight_recorder.jsonl"


def dump_flight_recorder(path: Optional[str] = None,
                         reason: str = "explicit") -> str:
    """Write the ring as JSONL: one `flight_dump` header record, then
    every ring record oldest-first (so the LAST line is the most recent
    completed step). Atomic (tmp + rename): a dump interrupted mid-write
    never leaves a half-written artifact over a previous good one.
    Returns the path written."""
    path = path or _default_flight_path()
    records = flight_records()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps({"kind": "flight_dump", "ts": time.time(),
                            "pid": os.getpid(), "reason": reason,
                            "n_records": len(records)}) + "\n")
        for rec in records:
            f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def install_flight_recorder(path: Optional[str] = None,
                            on_sigterm: bool = True):
    """Dump the flight recorder on unhandled exception (sys.excepthook,
    chained to the previous hook) and, by default, on SIGTERM (chained
    to any existing handler; installs an exiting default when none is
    set). Idempotent: a repeat install REPLACES the hook this module
    installed earlier (unwrapping to the original previous handler)
    instead of chaining to itself, so the dump is emitted exactly once
    per event no matter how many subsystems call this."""
    import sys

    prev_hook = sys.excepthook
    if getattr(prev_hook, "_ptn_flight_hook", False):
        prev_hook = prev_hook._ptn_prev

    def hook(tp, val, tb):
        try:
            dump_flight_recorder(path, reason=f"unhandled {tp.__name__}")
        except Exception:  # noqa: BLE001 — the dump must never mask
            pass           # the original crash
        prev_hook(tp, val, tb)

    hook._ptn_flight_hook = True
    hook._ptn_prev = prev_hook
    sys.excepthook = hook

    if on_sigterm:
        import signal
        prev_term = signal.getsignal(signal.SIGTERM)
        if getattr(prev_term, "_ptn_flight_hook", False):
            prev_term = prev_term._ptn_prev

        def on_term(signum, frame):
            try:
                dump_flight_recorder(path, reason=f"signal {signum}")
            except Exception:  # noqa: BLE001
                pass
            if callable(prev_term):
                prev_term(signum, frame)
            else:
                os._exit(128 + signum)

        on_term._ptn_flight_hook = True
        on_term._ptn_prev = prev_term

        try:
            signal.signal(signal.SIGTERM, on_term)
        except (ValueError, OSError):
            pass  # non-main thread / exotic platform


# ---------------------------------------------------------------------------
# Snapshots + exporters
# ---------------------------------------------------------------------------

def get_stats_snapshot() -> dict:
    """Point-in-time copy of every stat + phase aggregate (plain dict,
    JSON-serializable)."""
    with _LOCK:
        return {
            "ts": time.time(),
            "pid": os.getpid(),
            "counters": dict(_COUNTERS),
            "gauges": dict(_GAUGES),
            "histograms": {k: h.to_dict() for k, h in _HISTS.items()},
            "phases": {k: dict(v) for k, v in _PHASES.items()},
        }


def snapshot_to_jsonl(path: Optional[str] = None) -> str:
    """Append one snapshot line to a JSONL log (crash-safe: each line is
    flushed + fsynced, so a timed-out run still yields every snapshot
    written before the kill). Path defaults to FLAGS_monitor_export_path.
    Returns the path written."""
    if path is None:
        from .core.flags import FLAGS
        path = FLAGS.monitor_export_path
    if not path:
        raise ValueError(
            "no export path: pass one or set FLAGS_monitor_export_path")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    rec = {"kind": "stats_snapshot", **get_stats_snapshot()}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return path


_HELP_CACHE: Optional[Dict[str, str]] = None


def _stat_help() -> Dict[str, str]:
    """Stat name -> one-line description, parsed (once) from the
    docs/observability.md inventory table — the docs are the single
    source of truth for descriptions, and the bidirectional lint already
    guarantees every recorded stat has a row there. Missing docs (e.g.
    an installed wheel without the docs tree) degrade to no HELP lines,
    never an error on the scrape path."""
    global _HELP_CACHE
    if _HELP_CACHE is not None:
        return _HELP_CACHE
    help_: Dict[str, str] = {}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "docs", "observability.md")
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("| `"):
                    continue
                cells = [c.strip() for c in line.strip("|").split("|")]
                if len(cells) < 3:
                    continue
                name = cells[0].strip("`")
                desc = cells[2].replace("`", "").replace("\\", "")
                if name and desc:
                    help_[name] = " ".join(desc.split())
    except OSError:
        pass
    _HELP_CACHE = help_
    return help_


def prometheus_text() -> str:
    """Prometheus text exposition format. Dotted stat names become
    underscore-joined metric names under the paddle_tpu_ prefix; HELP
    text comes from the docs/observability.md inventory."""
    def mname(name):
        return "paddle_tpu_" + name.replace(".", "_")

    help_ = _stat_help()
    out = []

    def header(name, m, mtype):
        desc = help_.get(name)
        if desc:
            out.append(f"# HELP {m} {desc}")
        out.append(f"# TYPE {m} {mtype}")

    snap = get_stats_snapshot()
    for name, v in sorted(snap["counters"].items()):
        m = mname(name)
        header(name, m, "counter")
        out.append(f"{m} {v}")
    for name, v in sorted(snap["gauges"].items()):
        m = mname(name)
        header(name, m, "gauge")
        out.append(f"{m} {v}")
    for name, h in sorted(snap["histograms"].items()):
        m = mname(name)
        header(name, m, "histogram")
        cum = 0
        for le, c in h["buckets"].items():
            cum += c
            # Exposition format requires +Inf (capital I) — the internal
            # snapshot key stays "+inf" for JSON stability.
            le_s = "+Inf" if le == "+inf" else repr(float(le))
            out.append(f'{m}_bucket{{le="{le_s}"}} {cum}')
        out.append(f"{m}_sum {h['sum']}")
        out.append(f"{m}_count {h['count']}")
    # Prometheus ALERTS series from the SLO engine (monitor_alerts.py),
    # so one scrape carries both the stats and the alert states. Lazy
    # import: monitor_alerts imports this module at its top level.
    try:
        from .monitor_alerts import prometheus_alerts_text
        alerts = prometheus_alerts_text()
    except Exception:  # noqa: BLE001 — the scrape path never fails
        alerts = ""
    if alerts:
        out.append(alerts.rstrip("\n"))
    return "\n".join(out) + "\n"


def export_prometheus(path: str) -> str:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(prometheus_text())
    os.replace(tmp, path)
    return path


_http_server = None
_http_lock = threading.Lock()


def serve_prometheus(port: Optional[int] = None):
    """Tiny stdlib scrape endpoint: GET anything on 127.0.0.1:<port>
    returns prometheus_text(). port=None reads FLAGS_monitor_http_port
    (0 = disabled, returns None); an explicit port always serves (0
    binds an ephemeral port — read it back from server_address).
    Runs on a daemon thread; counts `monitor.http_scrapes`. Returns the
    HTTPServer (already-running instance on repeat calls)."""
    global _http_server
    if port is None:
        from .core.flags import FLAGS
        port = FLAGS.monitor_http_port
        if not port:
            return None
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            STAT_ADD("monitor.http_scrapes")
            body = prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass  # scrapes must not spam stderr

    with _http_lock:
        if _http_server is not None:
            return _http_server
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                              _Handler)
        threading.Thread(target=srv.serve_forever,
                         name="ptn-monitor-http", daemon=True).start()
        _http_server = srv
        return srv


def stop_prometheus():
    global _http_server
    with _http_lock:
        if _http_server is not None:
            _http_server.shutdown()
            _http_server.server_close()
            _http_server = None


def export_chrome_tracing(path: str) -> int:
    """Dump recorded phase events as chrome://tracing JSON (the format
    of the reference's tools/timeline.py, and of the native profiler's
    ptn_profiler_dump — profiler.export_chrome_tracing falls back to
    this when the native library is unavailable). Returns #events."""
    with _LOCK:
        events = list(_EVENTS)
    pid = os.getpid()
    trace = {"displayTimeUnit": "ms", "traceEvents": [
        {"name": nm, "ph": "X", "ts": ts_us, "dur": dur_us,
         "pid": pid, "tid": tid}
        for nm, ts_us, dur_us, tid in events]}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return len(events)


# ---------------------------------------------------------------------------
# Background exporter: periodic JSONL snapshots so even a run the
# harness timeout-kills leaves a usable log behind (the failure mode
# that produced BENCH_r05's `parsed: null`).
# ---------------------------------------------------------------------------

_exporter = None
_exporter_lock = threading.Lock()


class _Exporter(threading.Thread):
    def __init__(self, path, interval):
        super().__init__(name="ptn-monitor-exporter", daemon=True)
        self.path = path
        self.interval = interval
        self._stop = threading.Event()
        self._flush_lock = threading.Lock()
        self._flushed = False

    def run(self):
        while not self._stop.wait(self.interval):
            try:
                snapshot_to_jsonl(self.path)
            except OSError:
                pass  # transient FS trouble must not kill the thread

    def stop(self, flush=True):
        self._stop.set()
        if flush:
            # Exactly-once final flush: an explicit stop_exporter() plus
            # the atexit hook (or any racing double stop) must not write
            # the terminal snapshot twice.
            with self._flush_lock:
                if self._flushed:
                    return
                self._flushed = True
            try:
                snapshot_to_jsonl(self.path)
            except OSError:
                pass


def start_exporter(path: Optional[str] = None,
                   interval: Optional[float] = None):
    """Start (or return the running) background JSONL snapshot thread.
    Defaults: FLAGS_monitor_export_path / FLAGS_monitor_flush_interval_s.
    """
    global _exporter
    from .core.flags import FLAGS
    path = path or FLAGS.monitor_export_path
    if not path:
        raise ValueError(
            "no export path: pass one or set FLAGS_monitor_export_path")
    interval = interval or FLAGS.monitor_flush_interval_s
    try:
        serve_prometheus()  # FLAGS_monitor_http_port-gated (0 = no-op)
    except OSError:
        pass  # port in use must not kill the run being monitored
    with _exporter_lock:
        if _exporter is not None and _exporter.is_alive():
            return _exporter
        _exporter = _Exporter(path, interval)
        _exporter.start()
        import atexit
        atexit.register(stop_exporter)
        return _exporter


def stop_exporter(flush=True):
    global _exporter
    with _exporter_lock:
        if _exporter is not None:
            _exporter.stop(flush=flush)
            _exporter = None

"""Resilience subsystem: fault injection, retry/backoff, circuit
breaking, and the resilient training driver.

- faults.py        deterministic seedable fault injection, gated by
                   FLAGS_fault_spec (off by default, zero overhead)
- retry.py         deadline-aware jittered-exponential RetryPolicy
                   with a transient-vs-poison error taxonomy
- breaker.py       CLOSED -> OPEN -> HALF_OPEN -> CLOSED circuit
                   breaker for the serving/generation dispatch path
- trainer_guard.py NaN-step rollback, SIGTERM checkpoint-and-exit,
                   stuck-step watchdog for training loops

See docs/resilience.md for the fault-spec grammar, the retry taxonomy
and the recovery semantics.
"""
from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from .faults import (FaultInjector, FaultSpecError, TransientFault,
                     injector, parse_fault_spec, reset_injector)
from .retry import RetryExhausted, RetryPolicy, is_transient
from .trainer_guard import NanStepError, PreemptedError, TrainerGuard

__all__ = [
    "CLOSED", "HALF_OPEN", "OPEN", "CircuitBreaker",
    "FaultInjector", "FaultSpecError", "TransientFault",
    "injector", "parse_fault_spec", "reset_injector",
    "RetryExhausted", "RetryPolicy", "is_transient",
    "NanStepError", "PreemptedError", "TrainerGuard",
]

"""Circuit breaker: the serving-side load-shedding state machine.

States::

    CLOSED ──(threshold consecutive failures)──> OPEN
    OPEN ──(cooldown elapsed)──> HALF_OPEN
    HALF_OPEN ──(probe succeeds)──> CLOSED
    HALF_OPEN ──(probe fails)──> OPEN (fresh cooldown)

While OPEN every ``allow()`` answers False and the caller sheds the
request (serving maps this to OverloadedError → HTTP 503 with
Retry-After) instead of queueing work the backend cannot do. HALF_OPEN
admits a bounded number of probe requests; the first success closes the
breaker, a failure re-opens it.

Only *transient* failures (TransientFault, RetryExhausted — the
taxonomy of retry.py) should be recorded: a poison request failing is
client error, not backend sickness, and must not trip the breaker.
That classification is the caller's job; this class just counts.

Publishes ``resilience.breaker_state`` (gauge: 0 CLOSED, 1 HALF_OPEN,
2 OPEN), ``resilience.breaker_opens`` and ``resilience.breaker_shed``.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from ..core.flags import FLAGS
from ..monitor import STAT_ADD, STAT_SET, flight_record

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Thread-safe three-state breaker. ``failure_threshold=0``
    disables it: allow() is always True, state stays CLOSED."""

    def __init__(self, failure_threshold: Optional[int] = None,
                 cooldown_ms: Optional[float] = None,
                 half_open_probes: int = 1,
                 name: str = "serving",
                 clock=time.monotonic):
        self.failure_threshold = int(
            failure_threshold if failure_threshold is not None
            else FLAGS.serving_breaker_threshold)
        self.cooldown_ms = float(
            cooldown_ms if cooldown_ms is not None
            else FLAGS.serving_breaker_cooldown_ms)
        self.half_open_probes = max(1, int(half_open_probes))
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0

    # -- state ----------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self):
        # lock held
        if self._state == OPEN and (self._clock() - self._opened_at) \
                * 1000.0 >= self.cooldown_ms:
            self._transition(HALF_OPEN)
            self._probes_in_flight = 0

    def _transition(self, new: str):
        # lock held
        if new == self._state:
            return
        old, self._state = self._state, new
        STAT_SET("resilience.breaker_state", _STATE_GAUGE[new])
        flight_record("breaker_transition", breaker=self.name,
                      old=old, new=new)
        if new == OPEN:
            self._opened_at = self._clock()
            STAT_ADD("resilience.breaker_opens")

    # -- caller surface -------------------------------------------------

    def allow(self) -> bool:
        """May this request proceed? False = shed it now. HALF_OPEN
        admits up to half_open_probes concurrent probes."""
        if self.failure_threshold <= 0:
            return True
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN:
                if self._probes_in_flight < self.half_open_probes:
                    self._probes_in_flight += 1
                    return True
            STAT_ADD("resilience.breaker_shed")
            return False

    def would_allow(self) -> bool:
        """Side-effect-free preview of `allow()`: True if a request
        issued now would be admitted. Unlike `allow()` this never
        consumes a HALF_OPEN probe slot and never bumps the shed stat,
        so it is safe to call from health checks, gauges, and routing
        filters. The dispatch path must still call `allow()` (paired
        with record_success/record_failure) on the one request it
        actually sends."""
        if self.failure_threshold <= 0:
            return True
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            return (self._state == HALF_OPEN
                    and self._probes_in_flight < self.half_open_probes)

    def release_probe(self):
        """Return a HALF_OPEN probe slot without recording a verdict —
        for an admitted request that ended in a way that says nothing
        about backend health (e.g. the client sent a malformed
        request). No-op in every other state."""
        if self.failure_threshold <= 0:
            return
        with self._lock:
            if self._state == HALF_OPEN:
                self._probes_in_flight = max(
                    0, self._probes_in_flight - 1)

    def record_success(self):
        if self.failure_threshold <= 0:
            return
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._probes_in_flight = max(
                    0, self._probes_in_flight - 1)
                self._transition(CLOSED)

    def record_failure(self):
        if self.failure_threshold <= 0:
            return
        with self._lock:
            if self._state == HALF_OPEN:
                # the probe failed: straight back to OPEN for a fresh
                # cooldown
                self._probes_in_flight = max(
                    0, self._probes_in_flight - 1)
                self._consecutive_failures = self.failure_threshold
                self._transition(OPEN)
                return
            self._consecutive_failures += 1
            if self._state == CLOSED and \
                    self._consecutive_failures >= self.failure_threshold:
                self._transition(OPEN)

    def retry_after_s(self) -> float:
        """Seconds until an OPEN breaker will admit probes (the
        Retry-After header value); 0 when not OPEN."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            remaining = self.cooldown_ms / 1000.0 - (
                self._clock() - self._opened_at)
            return max(0.0, remaining)

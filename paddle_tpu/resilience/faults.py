"""Deterministic, seedable fault injection (FLAGS_fault_spec).

The chaos layer of the resilience subsystem: a process-wide registry of
armed faults that the executor, reader, and serving/generation dispatch
loops consult at fixed hook points. With FLAGS_fault_spec empty (the
default) every hook is a cached None-check — zero overhead on the hot
path.

Spec grammar (comma-separated ``kind:param=value[:param=value]``)::

    step_nan:p=0.01            corrupt the host-side fetch copies of a
                               step with NaN (the device state is NOT
                               touched — models the classic "bad batch
                               poisons the loss" failure)
    slow_step:ms=500:p=0.1     sleep before dispatch (stuck-step /
                               straggler model; p defaults to 1)
    transient_fail:p=0.02      raise TransientFault BEFORE device
                               dispatch (flaky-tunnel / infeed model;
                               retry-safe by construction)
    preempt_at:step=40         deliver SIGTERM to this process when the
                               hook sees global step 40 (one-shot;
                               models a scheduler preemption notice)

Each kind also accepts ``at=N`` (fire exactly on the Nth invocation of
the hook site, 1-based — the deterministic form tests use instead of
``p=``) and ``site=NAME`` (restrict to one hook site: ``executor``,
``reader``, ``serving``, ``generation``).

Determinism: the fire/skip decision for invocation *n* of a site is a
pure function of (FLAGS_fault_seed, site, kind, n) — timing and thread
interleaving cannot change which steps fault, so a chaos run is
replayable.

Hook points call :func:`injector` (returns None when no spec is armed)
then ``inj.pre_step(site, step=...)`` before dispatch and
``inj.corrupt_fetches(site, arrays)`` on the host-side fetch copies.
"""
from __future__ import annotations

import hashlib
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.flags import FLAGS
from ..monitor import STAT_ADD, flight_record

__all__ = ["TransientFault", "FaultSpecError", "FaultInjector",
           "injector", "parse_fault_spec", "reset_injector"]

_KINDS = ("step_nan", "slow_step", "transient_fail", "preempt_at")
_SITES = ("executor", "reader", "serving", "generation", "gen_prefill")


class TransientFault(RuntimeError):
    """A failure that is expected to succeed on retry (flaky transport,
    injected chaos, non-finite outputs from a recoverable glitch).
    The retryable side of the retry.py taxonomy."""


class FaultSpecError(ValueError):
    """FLAGS_fault_spec does not parse."""


class _Spec:
    __slots__ = ("kind", "p", "at", "ms", "step", "site")

    def __init__(self, kind: str, p: float = 0.0, at: int = 0,
                 ms: float = 0.0, step: int = -1,
                 site: Optional[str] = None):
        self.kind = kind
        self.p = p        # fire probability per invocation
        self.at = at      # fire exactly on the at-th invocation (1-based)
        self.ms = ms      # slow_step sleep duration
        self.step = step  # preempt_at global step
        self.site = site  # restrict to one hook site (None = any)

    def __repr__(self):
        parts = [self.kind]
        if self.p:
            parts.append(f"p={self.p}")
        if self.at:
            parts.append(f"at={self.at}")
        if self.ms:
            parts.append(f"ms={self.ms}")
        if self.step >= 0:
            parts.append(f"step={self.step}")
        if self.site:
            parts.append(f"site={self.site}")
        return ":".join(parts)


def parse_fault_spec(spec: str) -> List[_Spec]:
    """Parse the FLAGS_fault_spec grammar; raises FaultSpecError with
    the offending fragment on malformed input."""
    out: List[_Spec] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        fields = entry.split(":")
        kind = fields[0].strip()
        if kind not in _KINDS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r} in {entry!r} "
                f"(known: {', '.join(_KINDS)})")
        s = _Spec(kind)
        for field in fields[1:]:
            if "=" not in field:
                raise FaultSpecError(
                    f"malformed param {field!r} in {entry!r} "
                    f"(expected name=value)")
            name, _, raw = field.partition("=")
            name = name.strip()
            raw = raw.strip()
            try:
                if name == "p":
                    s.p = float(raw)
                    if not 0.0 <= s.p <= 1.0:
                        raise ValueError
                elif name == "at":
                    s.at = int(raw)
                    if s.at < 1:
                        raise ValueError
                elif name == "ms":
                    s.ms = float(raw)
                    if s.ms < 0:
                        raise ValueError
                elif name == "step":
                    s.step = int(raw)
                    if s.step < 0:
                        raise ValueError
                elif name == "site":
                    if raw not in _SITES:
                        raise ValueError
                    s.site = raw
                else:
                    raise FaultSpecError(
                        f"unknown param {name!r} in {entry!r}")
            except (ValueError, TypeError):
                raise FaultSpecError(
                    f"bad value {raw!r} for {name!r} in {entry!r}") \
                    from None
        if s.kind == "preempt_at" and s.step < 0:
            raise FaultSpecError(
                f"preempt_at needs step=N (got {entry!r})")
        if s.kind == "slow_step" and s.ms <= 0:
            raise FaultSpecError(
                f"slow_step needs ms=D (got {entry!r})")
        if s.kind in ("step_nan", "transient_fail") \
                and not s.p and not s.at:
            raise FaultSpecError(
                f"{s.kind} needs p= or at= (got {entry!r})")
        out.append(s)
    return out


def _decide(seed: int, site: str, kind: str, n: int) -> float:
    """Uniform [0,1) draw that is a pure function of its arguments.
    md5 rather than hash() so the decision survives PYTHONHASHSEED."""
    h = hashlib.md5(f"{seed}:{site}:{kind}:{n}".encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class FaultInjector:
    """Armed faults + per-(site, kind) invocation counters. Thread-safe:
    serving workers and the training loop share one injector."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self.specs = parse_fault_spec(spec)
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, str], int] = {}
        self._preempt_fired = False

    def _tick(self, site: str, kind: str) -> int:
        with self._lock:
            n = self._counters.get((site, kind), 0) + 1
            self._counters[(site, kind)] = n
            return n

    def _fires(self, s: _Spec, site: str) -> bool:
        if s.site is not None and s.site != site:
            return False
        n = self._tick(site, s.kind)
        if s.at:
            return n == s.at
        return _decide(self.seed, site, s.kind, n) < s.p

    # literal per-kind stat names (the observability doc lint requires
    # every documented name to exist as a string literal in code)
    _KIND_STATS = {"slow": "resilience.fault_slow",
                   "transient": "resilience.fault_transient",
                   "preempt": "resilience.fault_preempt",
                   "nan": "resilience.fault_nan"}

    def _record(self, kind: str, site: str, **fields):
        STAT_ADD("resilience.faults_injected")
        STAT_ADD(self._KIND_STATS[kind])
        flight_record("fault_injected", fault=kind, site=site, **fields)

    # -- hook points ----------------------------------------------------

    def pre_step(self, site: str, step: Optional[int] = None):
        """Called before device dispatch. May sleep (slow_step), raise
        TransientFault (transient_fail), or deliver SIGTERM to the
        process (preempt_at, one-shot)."""
        for s in self.specs:
            if s.kind == "slow_step":
                if s.site is not None and s.site != site:
                    continue
                # p=/at= gate the sleep; ungated slow_step fires every
                # invocation at matching sites
                if (s.p or s.at) and not self._fires(s, site):
                    continue
                self._record("slow", site, ms=s.ms)
                time.sleep(s.ms / 1000.0)
            elif s.kind == "transient_fail":
                if self._fires(s, site):
                    self._record("transient", site)
                    raise TransientFault(
                        f"injected transient fault at {site}")
            elif s.kind == "preempt_at" and step is not None:
                if s.site is not None and s.site != site:
                    continue
                if not self._preempt_fired and step == s.step:
                    self._preempt_fired = True
                    self._record("preempt", site, step=step)
                    signal.raise_signal(signal.SIGTERM)

    def corrupt_fetches(self, site: str,
                        arrays: List[np.ndarray]) -> bool:
        """Called on the HOST-side fetch copies after a step (a mutable
        list). step_nan pokes NaN into every float array — the
        device-side state is untouched, so a retry of the same step is
        clean. Returns True when a corruption was injected."""
        hit = False
        for s in self.specs:
            if s.kind != "step_nan":
                continue
            if self._fires(s, site):
                hit = True
        if hit:
            self._record("nan", site)
            for i, a in enumerate(arrays):
                if isinstance(a, np.ndarray) \
                        and np.issubdtype(a.dtype, np.floating) \
                        and a.size:
                    if not a.flags.writeable:
                        a = a.copy()
                        arrays[i] = a
                    a.reshape(-1)[0] = np.nan
        return hit


# Cached singleton keyed on the (spec, seed) pair so tests flipping
# FLAGS via set_flags get a fresh injector (with fresh counters) while
# steady-state callers pay one string compare.
_CACHE_LOCK = threading.Lock()
_CACHED: Tuple[Optional[str], int, Optional[FaultInjector]] = \
    (None, 0, None)


def injector() -> Optional[FaultInjector]:
    """The process-wide injector for the current FLAGS_fault_spec, or
    None when the spec is empty (the zero-overhead fast path)."""
    global _CACHED
    spec = FLAGS.fault_spec
    if not spec:
        if _CACHED[2] is not None:
            with _CACHE_LOCK:
                _CACHED = (None, 0, None)
        return None
    seed = FLAGS.fault_seed
    cached_spec, cached_seed, inj = _CACHED
    if inj is not None and cached_spec == spec and cached_seed == seed:
        return inj
    with _CACHE_LOCK:
        cached_spec, cached_seed, inj = _CACHED
        if inj is None or cached_spec != spec or cached_seed != seed:
            inj = FaultInjector(spec, seed)
            _CACHED = (spec, seed, inj)
        return inj


def reset_injector():
    """Drop the cached injector (tests: restart invocation counters
    without changing the spec)."""
    global _CACHED
    with _CACHE_LOCK:
        _CACHED = (None, 0, None)

"""Resilient training driver: NaN rollback, preemption, watchdog.

Wraps the plain ``exe.run`` training loop with the three recoveries the
reference framework bakes into its trainer (checkpoint notify +
error-clearing) and a TPU pod job needs in practice:

* **NaN-step rollback** — every ``snapshot_every`` steps the guard
  copies the persistable state to host memory; when a step's fetches
  come back non-finite (or the ``FLAGS_check_nan_inf`` guard raises
  FloatingPointError mid-step) the guard restores the snapshot and
  reports the step as *skipped* instead of crashing the run. With the
  default ``snapshot_every=1`` the recovery is exactly "the poisoned
  batch never happened". The restore also heals donation: a step that
  died mid-dispatch may have invalidated donated buffers, and the
  host-side snapshot replaces them wholesale.

* **SIGTERM preemption** — the guard chains onto the process SIGTERM
  handler; on delivery it only sets a flag, the in-flight step
  completes, then ``step()`` writes an atomic checkpoint (persistables
  + ``guard_state.json`` with the consumed-batch count, manifest-last
  commit) and raises PreemptedError. ``TrainerGuard.resume`` restores
  state and returns how many batches the stream must skip for a
  step-accurate restart.

* **Watchdog** — a daemon thread that notices a step exceeding
  ``watchdog_timeout_s`` and dumps the flight recorder once per stuck
  step (the post-mortem the run would otherwise take to its grave).

Usage::

    guard = TrainerGuard(exe, program, fetch_list=[loss],
                         checkpoint_dir="ckpt")
    for batch in stream:
        out = guard.step({"x": batch})   # None = NaN step skipped
    guard.close()

Deterministic-resume caveat: the executor's per-program step counter
(the PRNG fold-in) keeps advancing across skipped batches, so
bit-identical resume holds for deterministic programs (no dropout);
stochastic programs resume correctly but not bit-identically.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import goodput as _goodput
from ..core.scope import Scope, global_scope
from ..monitor import (STAT_ADD, dump_flight_recorder, flight_record)

__all__ = ["TrainerGuard", "PreemptedError", "NanStepError"]

_GUARD_STATE = "guard_state.json"


class PreemptedError(RuntimeError):
    """Raised by TrainerGuard.step after a SIGTERM-triggered checkpoint.
    Carries the checkpoint dir and the consumed-batch count."""

    def __init__(self, msg: str, checkpoint_dir: Optional[str],
                 global_step: int):
        super().__init__(msg)
        self.checkpoint_dir = checkpoint_dir
        self.global_step = global_step


class NanStepError(RuntimeError):
    """Raised when NaN steps exceed max_nan_skips — persistent NaN is a
    model/data bug, not a transient to paper over."""


def _persistable_names(program, scope) -> List[str]:
    return [v.name for v in program.list_vars()
            if v.persistable and not v.is_data and scope.has(v.name)]


class TrainerGuard:
    """Resilient wrapper around ``exe.run`` for a training program."""

    def __init__(self, exe, program, scope: Optional[Scope] = None,
                 fetch_list=None, checkpoint_dir: Optional[str] = None,
                 snapshot_every: int = 1, checkpoint_every: int = 0,
                 watchdog_timeout_s: float = 0.0,
                 max_nan_skips: int = 10,
                 install_sigterm: bool = True):
        self.exe = exe
        self.program = program
        self.scope = scope or global_scope()
        self.fetch_list = list(fetch_list or [])
        self.checkpoint_dir = checkpoint_dir
        self.snapshot_every = max(0, int(snapshot_every))
        self.checkpoint_every = max(0, int(checkpoint_every))
        self.max_nan_skips = int(max_nan_skips)

        self.global_step = 0        # batches consumed (skips included)
        self.nan_skips = 0
        self._snapshot: Dict[str, np.ndarray] = {}
        self._snapshot_step = -1
        self._preempt_requested = False
        self._preempt_draining = False
        self._closed = False

        self._prev_term = None
        self._installed_sigterm = False
        if install_sigterm:
            self._install_sigterm()

        self._watchdog: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()
        self._step_started: Optional[float] = None
        self._step_serial = 0
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        if self.watchdog_timeout_s > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="ptn-trainer-watchdog",
                daemon=True)
            self._watchdog.start()

    # -- SIGTERM --------------------------------------------------------

    def _install_sigterm(self):
        prev = signal.getsignal(signal.SIGTERM)

        def on_term(signum, frame):
            # flag only: the in-flight step finishes, step() checkpoints
            self._preempt_requested = True
            STAT_ADD("resilience.preemptions")
            flight_record("preempt_requested", step=self.global_step)
            if callable(prev) and prev not in (signal.SIG_DFL,
                                               signal.SIG_IGN):
                prev(signum, frame)

        try:
            signal.signal(signal.SIGTERM, on_term)
            self._prev_term = prev
            self._installed_sigterm = True
        except (ValueError, OSError):
            pass  # non-main thread: caller must deliver preemption
            # via request_preemption()

    def request_preemption(self):
        """Programmatic preemption notice (same path as SIGTERM)."""
        self._preempt_requested = True

    # -- watchdog -------------------------------------------------------

    def _watchdog_loop(self):
        poll = max(0.05, self.watchdog_timeout_s / 4.0)
        fired_for = -1
        while not self._watchdog_stop.wait(poll):
            started = self._step_started
            serial = self._step_serial
            if started is None or serial == fired_for:
                continue
            if time.monotonic() - started > self.watchdog_timeout_s:
                fired_for = serial
                STAT_ADD("resilience.watchdog_fires")
                flight_record("watchdog_stuck_step",
                              step=self.global_step,
                              stuck_seconds=round(
                                  time.monotonic() - started, 3))
                try:
                    dump_flight_recorder(reason="watchdog_stuck_step")
                except OSError:
                    pass

    # -- snapshot / rollback -------------------------------------------

    def _take_snapshot(self):
        snap = {}
        for n in _persistable_names(self.program, self.scope):
            snap[n] = np.array(self.scope.get_numpy(n), copy=True)
        self._snapshot = snap
        self._snapshot_step = self.global_step
        STAT_ADD("resilience.snapshots")

    def _rollback(self):
        t0 = time.perf_counter()
        for n, a in self._snapshot.items():
            self.scope.set(n, np.array(a, copy=True))
        _goodput.attribute("nan_rollback", time.perf_counter() - t0)
        STAT_ADD("resilience.rollbacks")
        flight_record("rollback", step=self.global_step,
                      snapshot_step=self._snapshot_step)

    # -- checkpoint / resume -------------------------------------------

    def checkpoint(self, dirname: Optional[str] = None) -> str:
        """Atomic checkpoint: every persistable via io's atomic per-var
        writes, then guard_state.json LAST as the commit marker."""
        from ..io import atomic_np_save, atomic_write_text
        dirname = dirname or self.checkpoint_dir
        if not dirname:
            raise ValueError("no checkpoint_dir configured")
        t0 = time.perf_counter()
        os.makedirs(dirname, exist_ok=True)
        names = _persistable_names(self.program, self.scope)
        for n in names:
            atomic_np_save(
                os.path.join(dirname,
                             n.replace("/", "%2F") + ".npy"),
                self.scope.get_numpy(n))
        atomic_write_text(
            os.path.join(dirname, _GUARD_STATE),
            json.dumps({"global_step": self.global_step,
                        "nan_skips": self.nan_skips,
                        "vars": names}))
        # on the preemption path the whole drain (this checkpoint) is
        # preempt_drain, not a routine checkpoint_save
        _goodput.attribute(
            "preempt_drain" if self._preempt_draining
            else "checkpoint_save",
            time.perf_counter() - t0)
        STAT_ADD("resilience.checkpoints")
        flight_record("checkpoint", step=self.global_step, dir=dirname)
        return dirname

    def resume(self, dirname: Optional[str] = None) -> int:
        """Restore a checkpoint written by checkpoint(); returns the
        consumed-batch count the data stream must skip."""
        dirname = dirname or self.checkpoint_dir
        t0 = time.perf_counter()
        state_path = os.path.join(dirname, _GUARD_STATE)
        with open(state_path) as f:
            state = json.load(f)
        for n in state["vars"]:
            path = os.path.join(dirname,
                                n.replace("/", "%2F") + ".npy")
            self.scope.set(n, np.load(path))
        self.global_step = int(state["global_step"])
        self.nan_skips = int(state.get("nan_skips", 0))
        self._snapshot = {}
        self._snapshot_step = -1
        _goodput.attribute("checkpoint_restore",
                           time.perf_counter() - t0)
        STAT_ADD("resilience.resumes")
        flight_record("resume", step=self.global_step, dir=dirname)
        return self.global_step

    @staticmethod
    def has_checkpoint(dirname: str) -> bool:
        return os.path.exists(os.path.join(dirname, _GUARD_STATE))

    # -- the step -------------------------------------------------------

    def _checkpoint_and_raise(self):
        where = None
        if self.checkpoint_dir:
            self._preempt_draining = True
            try:
                where = self.checkpoint(self.checkpoint_dir)
            finally:
                self._preempt_draining = False
        raise PreemptedError(
            f"preempted at step {self.global_step}"
            + (f"; checkpoint in {where}" if where else ""),
            where, self.global_step)

    def step(self, feed, fetch_list=None):
        """Run one training step. Returns the fetch list, or None when
        the step was NaN-poisoned and rolled back (the batch counts as
        consumed either way). Raises PreemptedError after a SIGTERM
        checkpoint."""
        if self._closed:
            raise RuntimeError("TrainerGuard is closed")
        if self._preempt_requested:
            self._checkpoint_and_raise()
        if self.snapshot_every and (
                self._snapshot_step < 0
                or self.global_step - self._snapshot_step
                >= self.snapshot_every):
            self._take_snapshot()

        fl = fetch_list if fetch_list is not None else self.fetch_list
        self._step_serial += 1
        self._step_started = time.monotonic()
        poisoned = None
        try:
            out = self.exe.run(self.program, feed=feed, fetch_list=fl,
                               scope=self.scope)
        except FloatingPointError as e:
            # FLAGS_check_nan_inf guard fired mid-step (with op/var
            # provenance): recoverable here, and the rollback also
            # replaces any donation-invalidated buffers
            poisoned, out = e, None
        finally:
            self._step_started = None

        if poisoned is None and out:
            for a in out:
                if isinstance(a, np.ndarray) \
                        and np.issubdtype(a.dtype, np.floating) \
                        and a.size and not np.all(np.isfinite(a)):
                    poisoned = FloatingPointError(
                        "non-finite fetch value")
                    break

        self.global_step += 1

        if poisoned is not None:
            self.nan_skips += 1
            STAT_ADD("resilience.nan_steps_skipped")
            flight_record("nan_step_skipped", step=self.global_step - 1,
                          error=repr(poisoned))
            self._rollback()
            if self.max_nan_skips and \
                    self.nan_skips > self.max_nan_skips:
                raise NanStepError(
                    f"{self.nan_skips} NaN steps exceed "
                    f"max_nan_skips={self.max_nan_skips}; last: "
                    f"{poisoned!r}") from poisoned
            out = None

        if self._preempt_requested:
            self._checkpoint_and_raise()
        if self.checkpoint_every and self.checkpoint_dir and \
                self.global_step % self.checkpoint_every == 0:
            self.checkpoint(self.checkpoint_dir)
        return out

    def close(self):
        """Stop the watchdog and restore the previous SIGTERM handler."""
        if self._closed:
            return
        self._closed = True
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
        if self._installed_sigterm:
            try:
                signal.signal(signal.SIGTERM, self._prev_term)
            except (ValueError, OSError, TypeError):
                pass
            self._installed_sigterm = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

"""Deadline-aware retry with jittered exponential backoff.

The taxonomy half of the resilience subsystem: a failure is either
*transient* (flaky transport, injected chaos, non-finite outputs from a
recoverable glitch — retrying the same work is expected to succeed) or
*poison* (malformed request, shape mismatch, verification failure —
retrying burns the attempt budget and fails anyway). RetryPolicy retries
the first kind invisibly and surfaces the second immediately, so a
poison batch fails only its own requests while transients never reach a
client.

Usage::

    policy = RetryPolicy()               # flags-defaulted knobs
    out = policy.call(lambda: run(feed)) # retries transients

The backoff for attempt n is ``base * 2^(n-1)`` milliseconds, capped at
``max_delay_ms``, jittered to a uniform draw in [half, full] of that
value (full jitter halves synchronized retry herds without starving the
deadline). A ``deadline_ms`` bounds the whole call including sleeps; on
expiry the last error is raised wrapped in RetryExhausted.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from ..core.flags import FLAGS
from ..monitor import STAT_ADD, STAT_OBSERVE
from .faults import TransientFault

__all__ = ["RetryPolicy", "RetryExhausted", "TransientFault",
           "is_transient"]

# ms buckets mirror serving/batcher.MS_BUCKETS (import would be
# circular: batcher -> engine -> retry)
_MS_BUCKETS = (1, 2, 5, 10, 20, 50, 100, 200, 500,
               1000, 2000, 5000, 10000)


class RetryExhausted(RuntimeError):
    """All attempts failed with transient errors. Carries the last
    underlying error as __cause__. Itself classified transient: an
    outer layer (circuit breaker) may still count it against health,
    but it is not poison."""


#: Error types that retrying is expected to cure. OSError covers the
#: flaky-transport class (the PERF.md tunnel resets); TimeoutError the
#: stuck-RPC class. ConnectionError is an OSError subclass.
_TRANSIENT_TYPES: Tuple[Type[BaseException], ...] = (
    TransientFault, RetryExhausted, OSError, TimeoutError)

#: Poison: retrying cannot help, fail fast. Checked BEFORE the
#: transient list so a poison subclass of a transient type stays
#: poison. FloatingPointError is the _nan_inf_guard signal — the
#: trainer guard handles it by rollback, not by replay.
_POISON_TYPES: Tuple[Type[BaseException], ...] = (
    ValueError, TypeError, KeyError, IndexError, AssertionError,
    FloatingPointError, NotImplementedError)


def is_transient(exc: BaseException) -> bool:
    """The retryable-error taxonomy. Unknown RuntimeErrors default to
    NOT retryable — replaying work with unknown failure semantics is
    how wrong answers get served."""
    if isinstance(exc, _POISON_TYPES):
        return False
    return isinstance(exc, _TRANSIENT_TYPES)


class RetryPolicy:
    """Bounded retry of transient failures with jittered exponential
    backoff. Thread-safe and reusable; one policy per subsystem."""

    def __init__(self, max_attempts: Optional[int] = None,
                 base_delay_ms: Optional[float] = None,
                 max_delay_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 is_retryable: Callable[[BaseException], bool]
                 = is_transient,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_attempts = int(max_attempts
                                if max_attempts is not None
                                else FLAGS.retry_max_attempts)
        self.base_delay_ms = float(base_delay_ms
                                   if base_delay_ms is not None
                                   else FLAGS.retry_base_ms)
        self.max_delay_ms = float(max_delay_ms
                                  if max_delay_ms is not None
                                  else FLAGS.retry_max_ms)
        self.deadline_ms = deadline_ms
        self.is_retryable = is_retryable
        self._sleep = sleep  # injectable for tests
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")

    def backoff_ms(self, attempt: int,
                   rng: Optional[random.Random] = None) -> float:
        """Backoff after failed attempt `attempt` (1-based): jittered
        exponential, in [half, full] of base * 2^(attempt-1), capped."""
        full = min(self.base_delay_ms * (2 ** (attempt - 1)),
                   self.max_delay_ms)
        draw = (rng.random() if rng is not None
                else random.random())
        return full * (0.5 + 0.5 * draw)

    def call(self, fn: Callable, *args, **kwargs):
        """Run fn, retrying transient failures. Raises the original
        error untouched when it is poison or the first attempt's budget
        is 1; raises RetryExhausted (last error as __cause__) when the
        attempt/deadline budget runs out."""
        deadline = (time.monotonic() + self.deadline_ms / 1000.0
                    if self.deadline_ms else None)
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: B036 — taxonomy decides
                if not self.is_retryable(e):
                    raise
                last = e
            if attempt == self.max_attempts:
                break
            delay_ms = self.backoff_ms(attempt)
            if deadline is not None and \
                    time.monotonic() + delay_ms / 1000.0 > deadline:
                STAT_ADD("resilience.retry_giveups")
                raise RetryExhausted(
                    f"deadline exhausted after {attempt} attempt(s): "
                    f"{last!r}") from last
            STAT_ADD("resilience.retries")
            STAT_OBSERVE("resilience.retry_backoff_ms", delay_ms,
                         buckets=_MS_BUCKETS)
            # goodput ledger: backoff sleep is attributed here at the
            # source; the executor subtracts the delta from its dispatch
            # span so the categories stay exclusive
            from .. import goodput as _goodput
            _goodput.attribute("retry_backoff", delay_ms / 1000.0)
            self._sleep(delay_ms / 1000.0)
        STAT_ADD("resilience.retry_giveups")
        raise RetryExhausted(
            f"gave up after {self.max_attempts} attempt(s): {last!r}") \
            from last

"""Python half of the C-ABI inference API (native/src/predictor.cc).

Reference: paddle/fluid/inference/capi/ — PD_NewAnalysisConfig /
PD_PredictorRun etc. give C callers a stable inference entry. Here the
saved artifact is the inference model written by
fluid.io.save_inference_model; the C side feeds raw buffers and reads
raw buffers back, never touching Python types.
"""
from __future__ import annotations

import numpy as np

__all__ = ["NativePredictor", "load_predictor"]


class NativePredictor:
    def __init__(self, model_dir):
        import paddle_tpu as fluid
        self._fluid = fluid
        self.scope = fluid.Scope()
        self.exe = fluid.Executor()
        with fluid.scope_guard(self.scope):
            prog, feeds, fetches = fluid.io.load_inference_model(
                model_dir, self.exe)
        self.program = prog
        self.feed_names = list(feeds)
        self.fetch_vars = fetches
        self._outputs = []

    def run_raw(self, feed_entries):
        """feed_entries: [(name, raw_bytes, dtype_str, shape_tuple)].
        Executes and caches outputs; returns the output count. The C
        side then reads each output via output_meta/output_bytes."""
        feed = {name: np.frombuffer(buf, dtype=np.dtype(dtype))
                .reshape(shape)
                for name, buf, dtype, shape in feed_entries}
        with self._fluid.scope_guard(self.scope):
            outs = self.exe.run(self.program, feed=feed,
                                fetch_list=self.fetch_vars)
        self._outputs = [np.ascontiguousarray(np.asarray(o))
                         for o in outs]
        return len(self._outputs)

    def output_meta(self, i):
        o = self._outputs[i]
        return (str(o.dtype), list(o.shape), int(o.nbytes))

    def output_bytes(self, i):
        return self._outputs[i].tobytes()


def load_predictor(model_dir) -> NativePredictor:
    return NativePredictor(model_dir)

"""DistributeTranspiler: rewrite one program into trainer + pserver
programs for parameter-server training.

Reference: python/paddle/fluid/transpiler/distribute_transpiler.py —
`transpile(trainer_id, program, pservers, trainers, sync_mode)` rewrites
the trainer program (grads -> send ops to their pserver, recv ops for
updated params, barriers in sync mode :216) and builds per-endpoint
pserver programs whose listen_and_serv op (distributed_ops/
listen_and_serv_op.cc) runs one optimizer sub-block per received grad.

TPU-native differences: tensors move host-side over the
paddle_tpu.distributed RPC runtime (DCN/gRPC analogue; SURVEY.md §2.8 —
ICI collectives don't apply to the PS topology); the pserver's optimizer
sub-blocks still lower to XLA and run on the pserver host's devices.
Whole-var placement uses a PSDispatcher; the reference's `slice_var_up`
block-slicing is not replicated (GSPMD sharding is the TPU answer to
oversized vars).
"""
from __future__ import annotations

from typing import Dict, List

from ..framework import Program
from .ps_dispatcher import PSDispatcher, RoundRobin
from .util import optimize_ops as _optimize_ops

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]


class DistributeTranspilerConfig:
    """Knob-compatible subset (reference distribute_transpiler.py:131)."""

    slice_var_up = False
    split_method = RoundRobin
    min_block_size = 8192
    sync_mode = True
    runtime_split_send_recv = False
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100


class DistributeTranspiler:
    def __init__(self, config: DistributeTranspilerConfig = None):
        self.config = config or DistributeTranspilerConfig()

    # ------------------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=None, startup_program=None,
                  current_endpoint=""):
        from ..framework import default_main_program, default_startup_program

        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = self.config.sync_mode if sync_mode is None \
            else sync_mode
        self.origin_program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.pserver_endpoints = (pservers.split(",")
                                  if isinstance(pservers, str) else
                                  list(pservers))

        block = self.origin_program.global_block()
        self._opt_ops = _optimize_ops(block)
        if not self._opt_ops:
            raise ValueError("transpile() needs a program with optimizer "
                             "ops (call minimize first)")
        self._param_of_grad: Dict[str, str] = {}
        params = []
        for op in self._opt_ops:
            p, g = op.inputs["Param"][0], op.inputs["Grad"][0]
            self._param_of_grad[g] = p
            params.append(block.var(p))

        # LR-scheduler ops (reference _get_lr_ops): the transitive
        # producers of the opt ops' LearningRate inputs. They move to the
        # pserver (run once per batch there) and leave the trainer.
        opt_set = {id(op) for op in self._opt_ops}
        lr_needed = {op.inputs["LearningRate"][0] for op in self._opt_ops
                     if op.inputs.get("LearningRate")}
        lr_ops_rev = []
        for op in reversed(block.ops):
            if id(op) in opt_set:
                continue
            if set(op.output_names()) & lr_needed:
                lr_ops_rev.append(op)
                lr_needed.update(n for n in op.input_names() if n)
        self._lr_ops = list(reversed(lr_ops_rev))
        lr_set = {id(op) for op in self._lr_ops}
        self._removed_op_indices = [
            i for i, op in enumerate(block.ops)
            if id(op) in opt_set or id(op) in lr_set]
        dispatcher: PSDispatcher = self.config.split_method(
            self.pserver_endpoints)
        self._ep_of_param = dict(
            zip([p.name for p in params], dispatcher.dispatch(params)))
        self._build_trainer_program()
        return self

    # ------------------------------------------------------------------
    def _build_trainer_program(self):
        """Clone the origin program minus optimizer ops; send each grad to
        its param's pserver, then recv updated params (sync mode blocks on
        the barrier inside the RPC layer)."""
        self.trainer_program = self.origin_program.clone()
        block = self.trainer_program.global_block()
        # drop optimizer AND lr-scheduler ops (indices match: clone is a
        # deepcopy preserving op order)
        removed = set(self._removed_op_indices)
        block.ops = [op for i, op in enumerate(block.ops)
                     if i not in removed]

        for g, p in self._param_of_grad.items():
            ep = self._ep_of_param[p]
            block.append_op(
                "send", inputs={"X": [g]}, outputs={},
                attrs={"endpoint": ep, "var_name": g,
                       "trainer_id": self.trainer_id,
                       "sync_mode": self.sync_mode},
                infer_shape=False)
        if self.sync_mode:
            block.append_op(
                "send_barrier", inputs={}, outputs={},
                attrs={"endpoints": self.pserver_endpoints,
                       "trainer_id": self.trainer_id}, infer_shape=False)
        for p, ep in self._ep_of_param.items():
            block.append_op(
                "recv", inputs={}, outputs={"Out": [p]},
                attrs={"endpoint": ep, "var_name": p,
                       "trainer_id": self.trainer_id}, infer_shape=False)
        if self.sync_mode:
            block.append_op(
                "fetch_barrier", inputs={}, outputs={},
                attrs={"endpoints": self.pserver_endpoints,
                       "trainer_id": self.trainer_id}, infer_shape=False)
        self.trainer_program._fp_cache = None

    # ------------------------------------------------------------------
    def get_trainer_program(self, wait_port=True) -> Program:
        return self.trainer_program

    def get_pserver_program(self, endpoint) -> Program:
        """Program = vars owned by this endpoint + one listen_and_serv op
        whose sub-blocks each run one param's optimizer ops."""
        from ..framework import Operator

        origin_block = self.origin_program.global_block()
        prog = Program()
        prog.random_seed = self.origin_program.random_seed
        block = prog.global_block()

        my_params = [p for p, ep in self._ep_of_param.items()
                     if ep == endpoint]

        def copy_var(n):
            if n and not block.has_var(n) and origin_block.has_var(n):
                v = origin_block.var(n)
                block.create_var(name=n, shape=v.shape, dtype=v.dtype,
                                 persistable=True, stop_gradient=True)

        # lr-scheduler block: runs ONCE per batch before the per-param
        # optimizer blocks (counters must tick once, not once per param)
        lr_block_idx = -1
        if self._lr_ops:
            sub = prog._create_block(parent_idx=0)
            for op in self._lr_ops:
                for n in list(op.input_names()) + list(op.output_names()):
                    copy_var(n)
                new_op = Operator(sub, op.type, op.inputs, op.outputs,
                                  op.attrs, op_id=op.id)
                sub.ops.append(new_op)
            prog._current_block_idx = 0
            lr_block_idx = sub.idx

        opt_block_of: Dict[str, int] = {}
        for p in my_params:
            sub = prog._create_block(parent_idx=0)
            for op in self._opt_ops:
                if op.inputs["Param"][0] != p:
                    continue
                for n in list(op.input_names()) + list(op.output_names()):
                    copy_var(n)
                sub.append_op(op.type, inputs=op.inputs,
                              outputs=op.outputs, attrs=op.attrs,
                              infer_shape=False)
            prog._current_block_idx = 0
            opt_block_of[p] = sub.idx

        block.append_op(
            "listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint,
                   "params": my_params,
                   "grad_of_param": {p: g for g, p in
                                     self._param_of_grad.items()},
                   "opt_block_of": opt_block_of,
                   "lr_block": lr_block_idx,
                   "sync_mode": self.sync_mode,
                   "Fanin": self.trainer_num},
            infer_shape=False)
        return prog

    def get_startup_program(self, endpoint, pserver_program=None) -> Program:
        """Init program for one pserver: only the vars it owns.

        Ops are copied PRESERVING their original op ids: initializer
        lowerings derive their PRNG streams from (program seed, op id)
        (core/lowering.py rng_for), so id-preserving copies make the
        pserver's param init bit-identical to a trainer running the full
        startup program — the reference gets this "for free" by shipping
        the same OpDescs around.
        """
        from ..framework import Operator

        my_params = {p for p, ep in self._ep_of_param.items()
                     if ep == endpoint}
        # optimizer state (accumulators, lr) lives with the param's opt
        # ops; lr-scheduler ops add their own state (step counters)
        needed = set(my_params)
        for op in self._opt_ops:
            if op.inputs["Param"][0] in my_params:
                needed.update(n for n in op.input_names() if n)
                needed.update(n for n in op.output_names() if n)
        for op in self._lr_ops:
            needed.update(n for n in op.input_names() if n)
            needed.update(n for n in op.output_names() if n)
        prog = Program()
        prog.random_seed = self.startup_program.random_seed
        block = prog.global_block()
        src = self.startup_program.global_block()
        for op in self.startup_program.global_block().ops:
            outs = [n for n in op.output_names() if n]
            if not outs or not all(o in needed for o in outs):
                continue
            for n in list(op.input_names()) + outs:
                if n and not block.has_var(n) and src.has_var(n):
                    v = src.var(n)
                    block.create_var(name=n, shape=v.shape, dtype=v.dtype,
                                     persistable=v.persistable,
                                     stop_gradient=True)
            new_op = Operator(block, op.type, op.inputs, op.outputs,
                              op.attrs, op_id=op.id)
            block.ops.append(new_op)
        prog._fp_cache = None
        return prog

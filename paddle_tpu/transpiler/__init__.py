"""Program-rewrite-based distribution (reference python/paddle/fluid/
transpiler/): collective data parallelism, parameter-server mode, geo-SGD.
"""
from .collective import Collective, GradAllReduce, LocalSGD  # noqa: F401
from .distribute_transpiler import (DistributeTranspiler,  # noqa: F401
                                    DistributeTranspilerConfig)
from .geo_sgd_transpiler import GeoSgdTranspiler  # noqa: F401
from .memory_optimization_transpiler import (memory_optimize,  # noqa: F401
                                             release_memory)
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin  # noqa: F401

__all__ = ["Collective", "GradAllReduce", "LocalSGD", "DistributeTranspiler",
           "DistributeTranspilerConfig", "GeoSgdTranspiler", "HashName",
           "PSDispatcher", "RoundRobin", "memory_optimize", "release_memory"]

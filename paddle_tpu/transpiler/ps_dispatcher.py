"""Parameter placement across parameter servers.

Reference: python/paddle/fluid/transpiler/ps_dispatcher.py — RoundRobin
and HashName policies deciding which pserver endpoint owns each variable.
"""
from __future__ import annotations

__all__ = ["PSDispatcher", "RoundRobin", "HashName"]


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    """Stable hash of the var name (reference uses the same idea so that
    trainer and pserver agree on placement without communication)."""

    @staticmethod
    def _hash(name: str) -> int:
        h = 0
        for c in name:
            h = (h * 31 + ord(c)) & 0x7FFFFFFF
        return h

    def dispatch(self, varlist):
        return [self._eps[self._hash(v.name if hasattr(v, "name") else str(v))
                          % len(self._eps)] for v in varlist]

"""Shared transpiler helpers."""
from __future__ import annotations

from ..core.registry import REGISTRY

__all__ = ["optimize_ops"]


def optimize_ops(block):
    """The block's parameter-update ops: inplace-registered ops carrying
    Param + Grad slots (the reference detects these via op role attrs,
    distribute_transpiler.py _is_opt_role_op)."""
    return [op for op in block.ops
            if REGISTRY.has(op.type) and REGISTRY.get(op.type).inplace
            and "Param" in op.inputs and "Grad" in op.inputs]

"""Collective transpilers: rewrite a single-process program for
multi-process data parallelism.

Reference: python/paddle/fluid/transpiler/collective.py — `Collective`
inserts c_gen_nccl_id + c_comm_init into the startup program (:113-123);
`GradAllReduce` (:178) appends c_allreduce_sum after each gradient with
multi-ring round-robin (:240-247) and scales by 1/nranks; `LocalSGD`
(:269) replaces per-step grad allreduce with periodic parameter averaging.

TPU mapping: there is no NCCL-id handshake — device topology comes from
the platform (jax.distributed.initialize on multi-host), so comm init
becomes the `c_comm_init_all` marker op (a no-op under single-host GSPMD).
The inserted c_allreduce_sum ops lower to psum inside shard_map, or to
identity under GSPMD jit where the partitioner inserts the collective
(ops/collective.py). ring_id round-robin maps rings to mesh axes
(parallel/mesh.axis_for_ring).
"""
from __future__ import annotations

from .util import optimize_ops as _optimize_ops

__all__ = ["Collective", "GradAllReduce", "LocalSGD"]

OpRole = type("OpRole", (), {"Forward": 0, "Backward": 1, "Optimize": 2})


class Collective:
    """Base: records job topology, rewrites startup with comm init."""

    def __init__(self, nrings=1):
        self.nrings = nrings
        self.nranks = 0
        self.rank = 0

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        self.nranks = len(endpoints)
        self.rank = rank
        self.startup_program = startup_program
        self.main_program = main_program
        self._transpile_startup_program(endpoints, current_endpoint)
        self._transpile_main_program()
        return self

    def _transpile_startup_program(self, endpoints, current_endpoint):
        # reference: c_gen_nccl_id (TCP bcast of the NCCL id,
        # c_gen_nccl_id_op.cc:68) + one c_comm_init per ring. On TPU the
        # marker op records topology; multi-host init happens in
        # paddle_tpu.distributed.launch/init_parallel_env.
        blk = self.startup_program.global_block()
        blk.append_op(
            "c_comm_init_all", inputs={}, outputs={},
            attrs={"endpoints": list(endpoints),
                   "current_endpoint": current_endpoint,
                   "rank": self.rank, "nranks": self.nranks,
                   "nrings": self.nrings},
            infer_shape=False)

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Insert allreduce-sum on every gradient (collective.py:178)."""

    def __init__(self, nrings=1):
        super().__init__(nrings)

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        opt_ops = _optimize_ops(block)
        grads = []
        for op in opt_ops:
            grads.extend(op.inputs["Grad"])
        grads = [g for g in dict.fromkeys(grads) if g]
        if not grads:
            return

        # last producer index of each grad
        producer = {}
        for i, op in enumerate(block.ops):
            for n in op.output_names():
                if n in grads:
                    producer[n] = i

        first_opt = min(block.ops.index(op) for op in opt_ops)
        # walk in reverse so earlier insertions don't shift later indices
        ring = 0
        from ..framework import Operator
        for g in sorted(grads, key=lambda g: -producer.get(g, first_opt)):
            idx = producer.get(g, first_opt - 1) + 1
            scale_op = Operator(
                block, "scale", {"X": [g]}, {"Out": [g]},
                {"scale": 1.0 / self.nranks, "bias": 0.0})
            ar_op = Operator(
                block, "c_allreduce_sum", {"X": [g]}, {"Out": [g]},
                {"ring_id": ring % self.nrings})
            block.ops[idx:idx] = [scale_op, ar_op]
            ring += 1
        self.main_program._fp_cache = None


class LocalSGD(Collective):
    """Periodic parameter averaging instead of per-step grad allreduce
    (collective.py:269; fleet DistributedStrategy.use_local_sgd)."""

    def __init__(self, nrings=1, k_steps=1):
        super().__init__(nrings)
        self.k_steps = k_steps

    def _transpile_main_program(self):
        from ..layers.control_flow import _CondBlockGuard
        from ..layers.learning_rate_scheduler import every_n_steps
        from ..framework import program_guard, unique_name

        block = self.main_program.global_block()
        params = [op.inputs["Param"][0] for op in _optimize_ops(block)]
        params = list(dict.fromkeys(params))
        if not params:
            return
        with program_guard(self.main_program, self.startup_program):
            cond = every_n_steps(
                self.k_steps,
                counter_name=unique_name.generate("@LOCAL_SGD_STEP@"))
            with _CondBlockGuard(cond):
                sub = self.main_program.current_block()
                for ring, p in enumerate(params):
                    sub.append_op(
                        "c_allreduce_sum", inputs={"X": [p]},
                        outputs={"Out": [p]},
                        attrs={"ring_id": ring % self.nrings},
                        infer_shape=False)
                    sub.append_op(
                        "scale", inputs={"X": [p]}, outputs={"Out": [p]},
                        attrs={"scale": 1.0 / self.nranks, "bias": 0.0},
                        infer_shape=False)
